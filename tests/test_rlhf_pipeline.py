"""Pipelined RLHF cycle: staleness bound, microbatched update, overlap.

The PR-4 invariants:

- off-by-one staleness: every batch the pipelined learner consumes was
  generated at most ONE weight version behind the version it is consumed
  at (RolloutPipeline's ticket gate);
- the microbatched gradient-accumulation update is NUMERICALLY the
  full-batch update (token-count weighting cancels the per-microbatch
  mean denominators), and its dispatch performs no host transfers;
- pipelined and sequential training from the same seed produce the SAME
  first update (bit-exact) and comparable learning on arithmetic;
- overlapping generation (host scoring included) with the donated update
  beats the sequential cycle in wall-clock.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.envs.llm import arithmetic_dataset
from rl_tpu.trainers.grpo import GRPOTrainer, PipelinedGRPOTrainer

# rlint runtime sanitizer: every lock created inside these tests is
# witnessed; any observed lock-order inversion fails the test at teardown
pytestmark = pytest.mark.usefixtures("lock_witness")


def _tiny(cls=GRPOTrainer, **kw):
    ds = arithmetic_dataset(n=64, max_operand=2)
    defaults = dict(num_prompts=4, group_repeats=4, max_prompt_len=8,
                    max_new_tokens=4, learning_rate=3e-3, kl_coeff=0.005)
    defaults.update(kw)
    return cls(ds, **defaults)


class TestStaleness:
    def test_off_by_one_invariant(self):
        """Every consumed batch's generation version is >= current - 1;
        steady state actually RUNS ahead (staleness 1, not 0)."""
        with _tiny(PipelinedGRPOTrainer, continuous_batching=False) as t:
            for _ in range(5):
                m = t.step()
                assert np.isfinite(m["loss"])
        assert len(t.staleness_history) == 5
        assert max(t.staleness_history) <= 1
        # first batch predates any update; after that the producer runs
        # one version behind — 0s throughout would mean no pipelining
        assert t.staleness_history[0] == 0
        assert t.staleness_history[-1] == 1
        assert t.policy_version.version == 5

    @pytest.mark.mesh
    def test_off_by_one_invariant_under_sharded_scheme(self):
        """PR-7: on a (batch, fsdp) mesh the pipeline publishes through
        ShardedSyncScheme (per-device shards, no full-replica gather) —
        the versioned-snapshot staleness semantics must be unchanged."""
        from rl_tpu.parallel import make_fsdp_mesh
        from rl_tpu.weight_update import ShardedSyncScheme

        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        with _tiny(PipelinedGRPOTrainer, continuous_batching=False,
                   mesh=mesh, fsdp_min_size_mb=0.0) as t:
            assert isinstance(t.scheme, ShardedSyncScheme)
            for _ in range(5):
                m = t.step()
                assert np.isfinite(m["loss"])
        assert max(t.staleness_history) <= 1
        assert t.staleness_history[0] == 0
        assert t.staleness_history[-1] == 1
        assert t.policy_version.version == 5

    @pytest.mark.slow
    def test_engine_backed_pipeline_steps(self):
        """Default PipelinedGRPOTrainer rides the continuous-batching
        engine inside the producer thread; versions advance, metrics stay
        finite, the staleness bound holds."""
        with _tiny(PipelinedGRPOTrainer) as t:
            assert t.collector.continuous_batching
            for _ in range(3):
                m = t.step()
                assert np.isfinite(m["reward"]) and np.isfinite(m["loss"])
        assert max(t.staleness_history) <= 1
        snap = t.metrics_snapshot()
        assert snap["updates"] >= 1.0
        assert snap["engine"]["tokens_generated"] > 0


class TestPipelinedParity:
    def test_first_update_bit_exact_vs_sequential(self):
        """The pipeline producer owns the trainer's key stream, so batch 1
        is the sequential trainer's batch 1 and update 1 matches exactly."""
        ts = _tiny()
        ts.step()
        with _tiny(PipelinedGRPOTrainer, continuous_batching=False) as tp:
            tp.step()
            for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(tp.params)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_learning_smoke_matches_sequential(self):
        """Off-by-one staleness must not break learning: both trainers
        improve on arithmetic and reach comparable eval accuracy."""
        steps = 40
        ts = _tiny(num_prompts=8, group_repeats=8)
        ts.train(steps)
        acc_seq = ts.evaluate()
        with _tiny(PipelinedGRPOTrainer, num_prompts=8, group_repeats=8,
                   continuous_batching=False) as tp:
            tp.train(steps)
            acc_pipe = tp.evaluate()
        h = tp.history["reward"]
        assert np.mean(h[-10:]) > np.mean(h[:10]), h
        assert acc_pipe >= acc_seq - 0.3, (acc_pipe, acc_seq)


class TestMicrobatchedUpdate:
    def test_accumulated_grad_equals_full_batch_grad(self):
        """Token-count weighting makes gradient accumulation exact: the
        loss is a global token mean, so sum(w_i * g_i) / sum(w_i) with
        w_i = microbatch token count IS the full-batch gradient."""
        t = _tiny()
        t._key, k = jax.random.split(t._key)
        batch = t.collector.collect(None, k)

        def grad_of(b):
            (_, _), g = jax.value_and_grad(
                lambda p: t.loss(p, b), has_aux=True
            )(t.params)
            return g

        full = grad_of(batch)
        mbs, B = 4, batch["tokens"].shape[0]
        acc, wsum = None, 0.0
        for i in range(B // mbs):
            mb = jax.tree.map(lambda x: x[i * mbs:(i + 1) * mbs], batch)
            w = float(t.loss.microbatch_weight(mb))
            g = grad_of(mb)
            acc = (jax.tree.map(lambda a: w * a, g) if acc is None
                   else jax.tree.map(lambda a, b: a + w * b, acc, g))
            wsum += w
        acc = jax.tree.map(lambda a: a / wsum, acc)
        for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(acc)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-6
            )

    def test_microbatched_step_tracks_full_batch_step(self):
        """End-to-end: one update with microbatch_size=4 lands within
        adam noise of the full-batch update (adam's first step is
        ~sign(g)*lr, so float-accumulation wobble on near-zero grads is
        amplified to ~1e-4 — well under the 3e-3 step size)."""
        ta = _tiny()
        tb = _tiny(microbatch_size=4)
        ta.step()
        tb.step()
        moved = 0.0
        for a, b in zip(jax.tree.leaves(ta.params), jax.tree.leaves(tb.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4
            )
            moved = max(moved, float(np.abs(np.asarray(a)).max()))
        assert moved > 0.0

    def test_update_dispatch_is_transfer_free(self):
        """The donated microbatched update must stage everything on
        device up front: dispatching it under transfer_guard('disallow')
        raises on any implicit host<->device copy."""
        t = _tiny(microbatch_size=4)
        t._key, k = jax.random.split(t._key)
        batch = jax.device_put(t.collector.collect(None, k))
        with jax.transfer_guard("disallow"):
            params, opt_state, dm = t._update(
                t.params, t.opt_state, batch, t._dm
            )
        t.params, t.opt_state, t._dm = params, opt_state, dm
        assert np.isfinite(float(jax.tree.leaves(params)[0].sum()))

    def test_remat_training_forward(self):
        """remat=True reruns the block forwards in the backward pass —
        same math, less activation memory; one step must match the
        non-remat trainer within adam-amplified float noise."""
        ta = _tiny()
        tb = _tiny(remat=True, remat_policy="dots", microbatch_size=8)
        ta.step()
        tb.step()
        for a, b in zip(jax.tree.leaves(ta.params), jax.tree.leaves(tb.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)

    def test_microbatch_size_must_divide_batch(self):
        with pytest.raises(ValueError, match="microbatch_size"):
            _tiny(microbatch_size=3)


class TestOverlapThroughput:
    @pytest.mark.slow
    def test_overlapped_beats_sequential_wall_clock(self):
        """With host-side reward work in the cycle (realistic scorers
        decode and parse), the pipeline hides the device update under the
        producer's scoring; the sequential trainer pays them serially.
        The scorer sleeps long enough that the hidden update dwarfs
        scheduler noise."""
        delay = 0.012  # per-row host scoring cost; B=32 rows -> ~0.4s/step

        def slow_scorer_factory(answers):
            from rl_tpu.envs.llm.reward import ExactMatchScorer
            em = ExactMatchScorer(answers)

            def scorer(history, toks):
                time.sleep(delay)
                return em(history, toks)

            return scorer

        ds = arithmetic_dataset(n=64, max_operand=2)
        kw = dict(num_prompts=4, group_repeats=8, max_prompt_len=8,
                  max_new_tokens=8, learning_rate=3e-3, kl_coeff=0.005,
                  scorer=slow_scorer_factory(ds.answers))
        steps = 5

        def run(t):
            t.step()  # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                t.step()
            # land everything dispatched
            jax.block_until_ready(jax.tree.leaves(t.params)[0])
            return time.perf_counter() - t0

        t_seq = run(GRPOTrainer(ds, **kw))
        with PipelinedGRPOTrainer(ds, continuous_batching=False, **kw) as tp:
            t_pipe = run(tp)
            assert max(tp.staleness_history) <= 1
        assert t_pipe < t_seq, (t_pipe, t_seq)
