"""rlint: static analyzer (R001–R007), baseline round-trip, LockWitness,
and the tier-1 gate holding rl_tpu/ at zero unsuppressed findings.

Rule fixtures are in-memory sources (``analyze_sources``) so each case
states exactly the code shape it exercises: a positive that must fire
and a negative that must stay silent. The gate test at the bottom is the
CI contract from ISSUE 8: ``python tools/rlint.py rl_tpu/`` exits 0 —
now under ``--strict`` (stale suppressions fail too). The IR tier
(R101–R105) has its own fixtures in tests/test_ir_audit.py.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from rl_tpu.analysis import (
    Baseline,
    LockWitness,
    analyze_paths,
    analyze_sources,
    hot_path,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# R001: host sync in hot path
# ---------------------------------------------------------------------------


class TestR001:
    def test_item_in_scan_body_flagged(self):
        src = """
import jax
import jax.numpy as jnp

def body(carry, x):
    bad = carry.item()
    return carry + x, bad

def run(xs):
    return jax.lax.scan(body, jnp.zeros(()), xs)
"""
        out = analyze_sources({"m": src}, rules=["R001"])
        assert [f.qualname for f in out] == ["body"]
        assert ".item()" in out[0].message

    def test_hot_path_decorated_loop_flagged(self):
        src = """
import numpy as np
from rl_tpu.analysis import hot_path

@hot_path(reason="dispatch loop")
def loop(dev_arrays):
    for a in dev_arrays:
        host = np.asarray(a)
    return host
"""
        out = analyze_sources({"m": src}, rules=["R001"])
        assert [f.qualname for f in out] == ["loop"]

    def test_reachability_through_helper(self):
        src = """
import jax

def helper(x):
    return float(x)

@jax.jit
def hot(x):
    return helper(x)
"""
        out = analyze_sources({"m": src}, rules=["R001"])
        assert [f.qualname for f in out] == ["helper"]
        assert "called from hot" in out[0].message

    def test_cold_function_not_flagged(self):
        src = """
import numpy as np

def checkpoint_meta(state):
    return {"step": int(state["step"]), "loss": float(state["loss"])}
"""
        assert analyze_sources({"m": src}, rules=["R001"]) == []

    def test_float_of_literal_not_flagged(self):
        src = """
import jax

@jax.jit
def hot(x):
    return x * float(1e-4)
"""
        assert analyze_sources({"m": src}, rules=["R001"]) == []


# ---------------------------------------------------------------------------
# R002: donation-after-use
# ---------------------------------------------------------------------------


class TestR002:
    SRC = """
import jax

def _step(state, batch):
    return state

step = jax.jit(_step, donate_argnums=(0,))

def bad(state, batch):
    new = step(state, batch)
    return state  # donated buffer referenced after dispatch

def ok(state, batch):
    state = step(state, batch)
    return state
"""

    def test_use_after_donation_flagged(self):
        out = analyze_sources({"m": self.SRC}, rules=["R002"])
        assert [f.qualname for f in out] == ["bad"]

    def test_rebound_not_flagged(self):
        out = analyze_sources({"m": self.SRC}, rules=["R002"])
        assert "ok" not in [f.qualname for f in out]

    def test_loop_carried_donation_flagged(self):
        src = """
import jax

def _step(state):
    return state

step = jax.jit(_step, donate_argnums=(0,))

def train(state):
    for _ in range(10):
        out = step(state)  # state donated on iter 0, reused on iter 1
    return out
"""
        out = analyze_sources({"m": src}, rules=["R002"])
        assert [f.qualname for f in out] == ["train"]


# ---------------------------------------------------------------------------
# R003: PRNG key reuse
# ---------------------------------------------------------------------------


class TestR003:
    def test_reuse_flagged(self):
        src = """
import jax

def sample(key):
    a = jax.random.uniform(key, (3,))
    b = jax.random.normal(key, (3,))
    return a + b
"""
        out = analyze_sources({"m": src}, rules=["R003"])
        assert len(out) == 1 and out[0].qualname == "sample"

    def test_split_between_uses_ok(self):
        src = """
import jax

def sample(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.uniform(k1, (3,))
    b = jax.random.normal(k2, (3,))
    return a + b
"""
        assert analyze_sources({"m": src}, rules=["R003"]) == []

    def test_exclusive_branches_ok(self):
        # the Bounded.rand shape that produced rlint's first false positive:
        # consumption on a `return`-terminated branch must not leak into the
        # fall-through path
        src = """
import jax

def rand(key, integer):
    if integer:
        return jax.random.randint(key, (3,), 0, 7)
    return jax.random.uniform(key, (3,))
"""
        assert analyze_sources({"m": src}, rules=["R003"]) == []

    def test_loop_carried_reuse_flagged(self):
        src = """
import jax

def rollout(key, n):
    total = 0.0
    for _ in range(n):
        total += jax.random.uniform(key, ())
    return total
"""
        out = analyze_sources({"m": src}, rules=["R003"])
        assert len(out) == 1 and "loop" in out[0].message


# ---------------------------------------------------------------------------
# R004: recompile hazards
# ---------------------------------------------------------------------------


class TestR004:
    def test_tracer_branch_flagged(self):
        src = """
import jax

@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
"""
        out = analyze_sources({"m": src}, rules=["R004"])
        assert len(out) == 1 and out[0].qualname == "f"

    def test_static_argname_branch_ok(self):
        src = """
from functools import partial
import jax

@partial(jax.jit, static_argnames=("training",))
def f(x, training):
    if training:
        return x * 2
    return x
"""
        assert analyze_sources({"m": src}, rules=["R004"]) == []

    def test_shape_branch_ok(self):
        src = """
import jax

@jax.jit
def f(x):
    if x.ndim == 2:
        return x.sum(axis=1)
    return x
"""
        assert analyze_sources({"m": src}, rules=["R004"]) == []

    def test_jit_in_loop_flagged(self):
        src = """
import jax

def train(xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v * 2)(x))
    return out
"""
        out = analyze_sources({"m": src}, rules=["R004"])
        assert len(out) == 1 and "loop" in out[0].message


# ---------------------------------------------------------------------------
# R006: ProgramRegistry bypass in models/ and trainers/
# ---------------------------------------------------------------------------


class TestR006:
    SRC = """
import jax
from functools import partial

def build(fn):
    step = jax.jit(fn, donate_argnums=(0,))
    return step

@jax.jit
def decorated(x):
    return x + 1

@partial(jax.jit, static_argnames=("n",))
def partial_decorated(x, n):
    return x * n
"""

    def test_flagged_inside_scope(self):
        out = analyze_sources({"rl_tpu.models.m": self.SRC}, rules=["R006"])
        assert len(out) == 3
        assert all("ProgramRegistry" in f.message for f in out)
        out = analyze_sources({"rl_tpu.trainers.m": self.SRC}, rules=["R006"])
        assert len(out) == 3

    def test_other_packages_not_flagged(self):
        # the rule is scoped: collectors/, ops/, tools keep raw jit freely
        assert analyze_sources({"rl_tpu.collectors.m": self.SRC},
                               rules=["R006"]) == []
        assert analyze_sources({"rl_tpu.ops.m": self.SRC}, rules=["R006"]) == []

    def test_registry_dispatch_not_flagged(self):
        src = """
from rl_tpu.compile import get_program_registry

def build(fn, cfg):
    reg = get_program_registry()
    return reg.register("m.step", fn, fingerprint=repr(cfg),
                        donate_argnums=(0,))
"""
        assert analyze_sources({"rl_tpu.models.m": src}, rules=["R006"]) == []


# ---------------------------------------------------------------------------
# R007: cross-thread shared-state hazard
# ---------------------------------------------------------------------------


class TestR007:
    SRC = """
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop)
        self._thread.start()

    def _loop(self):
        while True:
            self._count += 1
            with self._lock:
                self._total += 1

    def stats(self):
        return {"count": self._count, "total": self._peek()}

    def _peek(self):
        with self._lock:
            return self._total
"""

    def test_unlocked_cross_thread_attr_flagged(self):
        out = analyze_sources({"m": self.SRC}, rules=["R007"])
        assert len(out) == 1
        assert "_count" in out[0].message
        assert out[0].qualname.startswith("Worker")

    def test_locked_attr_not_flagged(self):
        out = analyze_sources({"m": self.SRC}, rules=["R007"])
        assert not any("_total" in f.message for f in out)

    def test_supervisor_spawn_target_flagged(self):
        src = """
class Service:
    def __init__(self, sup):
        self._sup = sup
        self._beats = 0

    def start(self):
        self._sup.spawn("svc", self._run)

    def _run(self):
        self._beats += 1

    def health(self):
        return self._beats
"""
        out = analyze_sources({"m": src}, rules=["R007"])
        assert len(out) == 1 and "_beats" in out[0].message

    def test_both_sides_locked_clean(self):
        src = """
import threading

class Service:
    def __init__(self, sup):
        self._sup = sup
        self._lock = threading.Lock()
        self._beats = 0

    def start(self):
        self._sup.spawn("svc", self._run)

    def _run(self):
        with self._lock:
            self._beats += 1

    def health(self):
        with self._lock:
            return self._beats
"""
        assert analyze_sources({"m": src}, rules=["R007"]) == []

    def test_thread_safe_primitives_excluded(self):
        src = """
import queue
import threading

class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._stop = threading.Event()

    def start(self):
        threading.Thread(target=self._loop).start()

    def _loop(self):
        while not self._stop.is_set():
            self._q.put(1)

    def drain(self):
        return self._q.get()

    def stop(self):
        self._stop.set()
"""
        assert analyze_sources({"m": src}, rules=["R007"]) == []

    def test_no_thread_no_finding(self):
        src = """
class Plain:
    def __init__(self):
        self._n = 0

    def bump(self):
        self._n += 1

    def read(self):
        return self._n
"""
        assert analyze_sources({"m": src}, rules=["R007"]) == []


# ---------------------------------------------------------------------------
# R005: static lock order
# ---------------------------------------------------------------------------


class TestR005:
    CYCLE = """
import threading

class A:
    _lock = threading.Lock()

    def use_b(self, b):
        with self._lock:
            b.locked_b()

    def locked_a(self):
        with self._lock:
            pass

class B:
    _lock = threading.Lock()

    def locked_b(self):
        with self._lock:
            pass

    def use_a(self, a):
        with self._lock:
            a.locked_a()
"""

    def test_cross_class_cycle_flagged(self):
        out = analyze_sources({"m": self.CYCLE}, rules=["R005"])
        assert out, "expected a lock-order cycle"
        assert any("cycle" in f.message for f in out)

    def test_consistent_order_ok(self):
        src = """
import threading

class A:
    _lock = threading.Lock()

    def f(self, b):
        with self._lock:
            b.g()

class B:
    _lock = threading.Lock()

    def g(self):
        with self._lock:
            pass
"""
        assert analyze_sources({"m": src}, rules=["R005"]) == []

    def test_self_deadlock_flagged(self):
        src = """
import threading

class A:
    _lock = threading.Lock()

    def f(self):
        with self._lock:
            with self._lock:
                pass
"""
        out = analyze_sources({"m": src}, rules=["R005"])
        assert len(out) == 1 and "self-deadlock" in out[0].message

    def test_rlock_reentry_ok(self):
        src = """
import threading

class A:
    _lock = threading.RLock()

    def f(self):
        with self._lock:
            with self._lock:
                pass
"""
        assert analyze_sources({"m": src}, rules=["R005"]) == []


# ---------------------------------------------------------------------------
# Baseline round-trip
# ---------------------------------------------------------------------------


class TestBaseline:
    SRC = """
import jax

def sample(key):
    a = jax.random.uniform(key, (3,))
    b = jax.random.normal(key, (3,))
    return a + b
"""

    def test_suppress_and_roundtrip(self, tmp_path):
        findings = analyze_sources({"m": self.SRC}, rules=["R003"])
        assert len(findings) == 1
        path = str(tmp_path / "baseline.json")
        b = Baseline(path=path)
        unsup, sup, stale = b.split(findings)
        assert len(unsup) == 1 and not sup and not stale

        b.add(findings[0], "intentional: fixture")
        b.save(path)
        b2 = Baseline.load(path)
        unsup, sup, stale = b2.split(findings)
        assert not unsup and len(sup) == 1 and not stale

        # stale detection: suppression survives, finding is gone
        unsup, sup, stale = b2.split([])
        assert not unsup and not sup and len(stale) == 1

    def test_reason_required(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        with open(path, "w") as f:
            json.dump({"suppressions": [{"fingerprint": "abc", "reason": ""}]}, f)
        with pytest.raises(ValueError, match="reason"):
            Baseline.load(path)

    def test_fingerprint_survives_line_shift(self):
        shifted = "\n\n\n# comment\n" + self.SRC
        f1 = analyze_sources({"m": self.SRC}, rules=["R003"])[0]
        f2 = analyze_sources({"m": shifted}, rules=["R003"])[0]
        assert f1.line != f2.line
        assert f1.fingerprint == f2.fingerprint


# ---------------------------------------------------------------------------
# LockWitness (runtime)
# ---------------------------------------------------------------------------


class TestLockWitness:
    def test_two_thread_inversion_detected(self):
        w = LockWitness()
        with w:
            a = threading.Lock()
            b = threading.Lock()

            def t1():
                with a:
                    time.sleep(0.01)
                    with b:
                        pass

            def t2():
                # start after t1 releases: we want the ORDER FLIP observed,
                # not the actual deadlock
                time.sleep(0.05)
                with b:
                    with a:
                        pass

            ts = [threading.Thread(target=t1), threading.Thread(target=t2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

        inv = w.inversions()
        assert len(inv) == 1
        assert w.stats()["inversions"] == 1

    def test_consistent_order_clean(self):
        w = LockWitness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        assert w.inversions() == []
        assert w.stats()["edges"] == 1

    def test_rlock_reentry_not_inversion(self):
        w = LockWitness()
        with w:
            r = threading.RLock()
            with r:
                with r:
                    pass
        assert w.inversions() == []

    def test_condition_and_queue_survive(self):
        # Condition lifts _release_save/_acquire_restore/_is_owned from the
        # wrapped lock; a Queue handoff across threads exercises all three
        import queue

        w = LockWitness()
        with w:
            q = queue.Queue()
            got = []

            def consumer():
                got.append(q.get(timeout=5))

            t = threading.Thread(target=consumer)
            t.start()
            q.put("x")
            t.join()
        assert got == ["x"]
        assert w.inversions() == []

    def test_disarm_restores_factories(self):
        orig_lock, orig_rlock = threading.Lock, threading.RLock
        w = LockWitness()
        w.arm()
        assert threading.Lock is not orig_lock
        w.disarm()
        assert threading.Lock is orig_lock
        assert threading.RLock is orig_rlock


# ---------------------------------------------------------------------------
# hot_path decorator is a transparent no-op at runtime
# ---------------------------------------------------------------------------


def test_hot_path_decorator_noop():
    @hot_path(reason="test")
    def f(x):
        return x + 1

    @hot_path
    def g(x):
        return x * 2

    assert f(1) == 2 and g(2) == 4
    assert f.__rl_tpu_hot_path__ and g.__rl_tpu_hot_path__
    assert f.__name__ == "f"


# ---------------------------------------------------------------------------
# conftest transfer-guard mode for marked hot-path tests
# ---------------------------------------------------------------------------


@pytest.mark.hot_path_guard
def test_hot_path_guard_marker_blocks_implicit_transfers():
    # on the CPU backend d2h is zero-copy (unguarded), so the observable
    # implicit transfer here is host→device: a numpy operand silently
    # uploaded into a device computation
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.arange(4)  # device computation, no transfer
    with pytest.raises(Exception, match="[Dd]isallow"):
        jnp.sin(np.arange(4.0))  # implicit h2d of the numpy operand
    # explicit transfers stay allowed: the guard targets *implicit* syncs
    assert jax.device_get(x).tolist() == [0, 1, 2, 3]
    y = jax.device_put(np.arange(4))
    assert int(jax.device_get(y)[3]) == 3


def test_unmarked_tests_keep_implicit_transfers():
    import jax.numpy as jnp
    import numpy as np

    assert jnp.sin(np.arange(3.0)).shape == (3,)
    assert np.asarray(jnp.arange(3)).tolist() == [0, 1, 2]


# ---------------------------------------------------------------------------
# The tier-1 gate: rl_tpu/ is clean under the checked-in baseline
# ---------------------------------------------------------------------------


class TestPackageGate:
    def test_zero_unsuppressed_findings(self):
        findings = analyze_paths([os.path.join(REPO, "rl_tpu")], root=REPO)
        baseline = Baseline.load(os.path.join(REPO, ".rlint-baseline.json"))
        unsup, sup, stale = baseline.split(findings)
        assert not unsup, "unsuppressed rlint findings:\n" + "\n".join(
            f.format() for f in unsup
        )
        assert not stale, "stale suppressions (finding no longer fires): " + str(
            [s.get("fingerprint") for s in stale]
        )

    def test_every_suppression_has_reason(self):
        baseline = Baseline.load(os.path.join(REPO, ".rlint-baseline.json"))
        assert baseline.suppressions, "baseline unexpectedly empty"
        for s in baseline.suppressions:
            assert s.get("reason", "").strip(), f"no reason: {s}"
            assert s["reason"] != "PENDING", f"untriaged suppression: {s}"

    def test_cli_gate_exits_zero(self):
        # --strict: stale suppressions are failures, not warnings — the
        # committed baseline must be exactly the live finding set
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "rlint.py"),
             "rl_tpu/", "--strict"],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_artifact_counts_consistent(self):
        path = os.path.join(REPO, "RLINT_pr15.json")
        with open(path) as f:
            art = json.load(f)
        assert art["tool"] == "rlint"
        total = art["total"]
        assert total["unsuppressed"] == 0
        assert total["found"] == total["suppressed"]
        assert total["found"] == sum(r["found"] for r in art["by_rule"].values())
        assert total["fixed_in_prs"] == len(art["fixed"])
        # the ledger carries PR 8's two genuine fixes forward
        assert any(e["pr"] == 8 and e["rule"] == "R003" for e in art["fixed"])
        assert any(e["pr"] == 8 and e["rule"] == "R001" for e in art["fixed"])
        # the deep tier is part of the committed summary: AST + IR rules,
        # every audit-set program accounted for, zero findings
        for rid in ("R007", "R101", "R102", "R103", "R104", "R105"):
            assert rid in art["rules"] and rid in art["by_rule"]
        ir = art["ir"]
        assert all(v == "ok" for v in ir["status"].values())
        assert ir["programs_audited"] >= 5
        assert "offpolicy.k_updates" in ir["by_program"]
        for name, rec in ir["by_program"].items():
            assert rec["findings"] == 0, name
        kup = ir["by_program"]["offpolicy.k_updates"]
        assert kup["donated_declared"] > 0 and kup["donated_honored"] > 0


class TestDiffMode:
    """--diff gating logic (the IR set itself is exercised in
    tests/test_ir_audit.py; here the compile is stubbed out)."""

    def _run(self, monkeypatch, changed, argv):
        import tools.rlint as rlint

        calls = {}

        def fake_run_ir(baseline_path, *, fresh_store):
            calls["fresh_store"] = fresh_store
            from rl_tpu.analysis.ir import IRAuditor

            return IRAuditor(baseline_path=baseline_path), {"stub": "ok"}

        monkeypatch.setattr(rlint, "changed_files", lambda rev: changed)
        monkeypatch.setattr(rlint, "run_ir", fake_run_ir)
        rc = rlint.main(argv)
        return rc, calls

    def test_ir_sensitive_change_reruns_ir_with_persistent_store(
        self, monkeypatch, capsys
    ):
        rc, calls = self._run(
            monkeypatch,
            ["rl_tpu/trainers/off_policy.py", "docs/static_analysis.md"],
            ["--diff", "HEAD~1"],
        )
        assert rc == 0
        # persistent store: unchanged-fingerprint programs load + skip
        assert calls == {"fresh_store": False}
        assert "IR set" in capsys.readouterr().out

    def test_non_ir_change_skips_ir(self, monkeypatch, capsys):
        rc, calls = self._run(
            monkeypatch, ["rl_tpu/obs/metrics.py"], ["--diff", "HEAD~1"]
        )
        assert rc == 0
        assert calls == {}  # run_ir never invoked
        assert "no IR-sensitive modules touched" in capsys.readouterr().out

    def test_empty_diff_is_clean_and_fast(self, monkeypatch, capsys):
        rc, calls = self._run(monkeypatch, [], ["--diff", "HEAD"])
        assert rc == 0 and calls == {}
        out = capsys.readouterr().out
        assert "0 finding(s)" in out

    def test_explicit_ir_flag_uses_fresh_store(self, monkeypatch):
        rc, calls = self._run(
            monkeypatch, ["rl_tpu/obs/metrics.py"], ["--diff", "HEAD~1", "--ir"]
        )
        assert rc == 0
        assert calls == {"fresh_store": True}
