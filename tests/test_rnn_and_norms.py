"""RNN modules, VecNorm, image transforms, ValueNorm/PopArt tests
(strategy mirrors reference test/modules/test_rnn.py reset semantics and
transforms tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import ArrayDict, Bounded, Composite, Unbounded
from rl_tpu.envs import (
    RewardSum,
    CenterCrop,
    GrayScale,
    InitTracker,
    Resize,
    ToFloatImage,
    TransformedEnv,
    VecNorm,
    VmapEnv,
    check_env_specs,
    rollout,
)
from rl_tpu.envs.base import EnvBase
from rl_tpu.modules import (
    GRUModule,
    LSTMModule,
    ValueNorm,
    popart_update,
    set_recurrent_mode,
)
from rl_tpu.testing import CountingEnv

KEY = jax.random.key(0)


@pytest.mark.parametrize("mod_cls", [LSTMModule, GRUModule], ids=["lstm", "gru"])
class TestRNN:
    @pytest.mark.slow
    def test_sequence_shapes(self, mod_cls):
        rnn = mod_cls(input_size=3, hidden_size=8)
        td = ArrayDict(
            observation=jax.random.normal(KEY, (2, 5, 3)),
            is_init=jnp.zeros((2, 5), bool),
        )
        params = rnn.init(KEY, td)
        out = rnn(params, td)
        assert out["embed"].shape == (2, 5, 8)

    @pytest.mark.slow
    def test_step_equals_sequence(self, mod_cls):
        """Step-mode unroll must equal sequence-mode scan (the reference's
        python-cell vs fused-kernel equivalence test)."""
        rnn = mod_cls(input_size=3, hidden_size=8)
        obs = jax.random.normal(KEY, (2, 6, 3))
        is_init = jnp.zeros((2, 6), bool).at[:, 0].set(True).at[0, 3].set(True)
        td_seq = ArrayDict(observation=obs, is_init=is_init)
        params = rnn.init(KEY, td_seq)
        seq_out = rnn(params, td_seq)["embed"]

        with set_recurrent_mode("step"):
            td = ArrayDict(observation=obs[:, 0], is_init=is_init[:, 0])
            outs = []
            for t in range(6):
                td = td.set("observation", obs[:, t]).set("is_init", is_init[:, t])
                td = rnn(params, td)
                outs.append(td["embed"])
        step_out = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(np.asarray(seq_out), np.asarray(step_out), atol=1e-5)

    @pytest.mark.slow
    def test_reset_isolates_episodes(self, mod_cls):
        """With a reset at t, the output from t onward must match a fresh
        sequence started at t."""
        rnn = mod_cls(input_size=2, hidden_size=4)
        obs = jax.random.normal(KEY, (1, 8, 2))
        params = rnn.init(KEY, ArrayDict(observation=obs, is_init=jnp.zeros((1, 8), bool)))
        is_init = jnp.zeros((1, 8), bool).at[0, 4].set(True)
        full = rnn(params, ArrayDict(observation=obs, is_init=is_init))["embed"]
        fresh = rnn(
            params,
            ArrayDict(
                observation=obs[:, 4:],
                is_init=jnp.zeros((1, 4), bool).at[0, 0].set(True),
            ),
        )["embed"]
        np.testing.assert_allclose(np.asarray(full[:, 4:]), np.asarray(fresh), atol=1e-5)

    @pytest.mark.slow
    def test_collector_rollout_with_rnn_policy(self, mod_cls):
        """RNN policy through the scan collector: carry via the recurrent
        keys must thread through exploration-style carry."""
        from rl_tpu.collectors import Collector
        from rl_tpu.modules import MLP, TDModule

        env = VmapEnv(CountingEnv(max_count=100), 2)
        rnn = mod_cls(input_size=1, hidden_size=4)
        head = TDModule(MLP(out_features=2), ["embed"], ["logits"])
        td0 = ArrayDict(observation=jnp.zeros((2, 1)), is_init=jnp.ones((2,), bool))
        k1, k2 = jax.random.split(KEY)
        params = {"rnn": rnn.init(k1, td0)}
        td0 = rnn._step(params["rnn"], td0)
        params["head"] = head.init(k2, td0)

        def policy(params, td, key):
            with set_recurrent_mode("step"):
                # recurrent carry rides in "exploration" (collector carries it)
                if ("exploration", "rnn") in td:
                    for i, k in enumerate(rnn._carry_keys()):
                        td = td.set(k, td["exploration", "rnn", f"c{i}"])
                td = td.set("is_init", td["done"] | (("exploration", "rnn") not in td))
                td = rnn._step(params["rnn"], td)
                td = head(params["head"], td)
                action = jnp.argmax(td["logits"], axis=-1)
                carry = ArrayDict(
                    rnn=ArrayDict(
                        {f"c{i}": td[k] for i, k in enumerate(rnn._carry_keys())}
                    )
                )
                return td.set("action", action).set("exploration", carry)

        coll = Collector(
            env,
            policy,
            frames_per_batch=8,
            policy_state=ArrayDict(
                rnn=ArrayDict(
                    {f"c{i}": jnp.zeros((2, 4)) for i in range(rnn.num_carry)}
                )
            ),
        )
        batch, cstate = jax.jit(coll.collect)(params, coll.init(KEY))
        assert batch["embed"].shape == (4, 2, 4)


class _PixelEnv(EnvBase):
    @property
    def observation_spec(self):
        return Composite(pixels=Bounded(shape=(16, 16, 3), low=0, high=255, dtype=jnp.uint8))

    @property
    def action_spec(self):
        from rl_tpu.data import Categorical

        return Categorical(n=2)

    def _reset(self, key):
        px = jax.random.randint(key, (16, 16, 3), 0, 256, jnp.int32).astype(jnp.uint8)
        return ArrayDict(px=px), ArrayDict(pixels=px)

    def _step(self, state, action, key):
        px = state["px"]
        return state, ArrayDict(pixels=px), jnp.asarray(1.0), jnp.asarray(False), jnp.asarray(False)


class TestImageTransforms:
    @pytest.mark.slow
    def test_pipeline_spec_conformance(self):
        env = TransformedEnv(
            _PixelEnv(),
            [ToFloatImage(), GrayScale(), Resize(8, 8), CenterCrop(6, 6)],
        )
        check_env_specs(env, KEY)
        _, td = env.reset(KEY)
        assert td["pixels"].shape == (6, 6, 1)
        assert td["pixels"].dtype == jnp.float32
        assert float(td["pixels"].max()) <= 10.0  # scaled to ~[0,1]

    def test_grayscale_luma(self):
        g = GrayScale()
        x = jnp.ones((4, 4, 3))
        y = g._apply_leaf(x)
        np.testing.assert_allclose(np.asarray(y), 1.0, rtol=1e-3)  # luma weights sum to 0.9999


class TestVecNorm:
    @pytest.mark.slow
    def test_running_stats_whiten(self):
        class BiasedEnv(EnvBase):
            @property
            def observation_spec(self):
                return Composite(observation=Unbounded(shape=(2,)))

            @property
            def action_spec(self):
                from rl_tpu.data import Categorical

                return Categorical(n=2)

            def _reset(self, key):
                return ArrayDict(), ArrayDict(observation=jnp.asarray([10.0, -5.0]) + jax.random.normal(key, (2,)))

            def _step(self, state, action, key):
                obs = jnp.asarray([10.0, -5.0]) + jax.random.normal(key, (2,))
                return state, ArrayDict(observation=obs), jnp.asarray(1.0), jnp.asarray(False), jnp.asarray(False)

        env = TransformedEnv(VmapEnv(BiasedEnv(), 16), VecNorm())
        steps = rollout(env, KEY, max_steps=64)
        obs = np.asarray(steps["next", "observation"])
        # after burn-in the normalized obs are ~zero-mean unit-var
        late = obs[32:].reshape(-1, 2)
        assert np.abs(late.mean(0)).max() < 0.5
        assert abs(late.std(0).mean() - 1.0) < 0.5

    def test_frozen_does_not_update(self):
        t = VecNorm(frozen=True)
        td = ArrayDict(
            observation=jnp.ones((4, 2)),
            done=jnp.zeros((4,), bool),
            terminated=jnp.zeros((4,), bool),
            truncated=jnp.zeros((4,), bool),
        )
        st = t.init(td)
        st2, _ = t.step(st, td)
        np.testing.assert_array_equal(
            np.asarray(st["observation", "count"]), np.asarray(st2["observation", "count"])
        )


class TestValueNorm:
    def test_normalize_roundtrip(self):
        vn = ValueNorm()
        st = vn.init()
        targets = jax.random.normal(KEEP := jax.random.key(2), (256,)) * 5 + 3
        st = vn.update(st, targets)
        z = vn.normalize(st, targets)
        assert abs(float(z.mean())) < 0.2 and abs(float(z.std()) - 1.0) < 0.2
        back = vn.denormalize(st, z)
        np.testing.assert_allclose(np.asarray(back), np.asarray(targets), rtol=1e-4)

    def test_popart_preserves_predictions(self):
        import flax.linen as nn

        vn = ValueNorm()
        old = vn.init()
        targets1 = jnp.asarray([1.0, 2.0, 3.0])
        old = vn.update(old, targets1)
        head = nn.Dense(1)
        x = jax.random.normal(KEY, (8, 4))
        params = head.init(KEY, x)["params"]
        pred_before = vn.denormalize(old, head.apply({"params": params}, x)[..., 0])

        new = vn.update(old, jnp.asarray([50.0, 60.0]))
        params2 = popart_update(params, old, new, vn)
        pred_after = vn.denormalize(new, head.apply({"params": params2}, x)[..., 0])
        np.testing.assert_allclose(np.asarray(pred_before), np.asarray(pred_after), rtol=1e-4)


class TestDoneStateDispatch:
    def test_vecnorm_stats_survive_scalar_env_autoreset(self):
        """Scalar env: stats must keep accumulating across episode resets
        (the shape heuristic cannot see this; Transform.on_done does)."""
        env = TransformedEnv(CountingEnv(max_count=3), VecNorm())
        steps = rollout(env, KEY, max_steps=12)  # crosses 4 episode resets
        # re-run the count-tracking manually: final count must be ~12 samples
        env2 = TransformedEnv(CountingEnv(max_count=3), VecNorm())
        s2, td = env2.reset(KEY)
        for _ in range(9):
            td2 = env2.rand_action(td, KEY)
            s2, _, td = env2.step_and_reset(s2, td2)
        cnt = float(np.asarray(s2["transforms"]["observation", "count"]))
        assert cnt > 3.5, f"VecNorm count reset at episode boundary: {cnt}"

    def test_rewardsum_still_resets_per_env(self):
        env = TransformedEnv(VmapEnv(CountingEnv(max_count=3), 2), RewardSum())
        steps = rollout(env, KEY, max_steps=7)
        ep = np.asarray(steps["next", "episode_reward"])
        np.testing.assert_allclose(ep[:, 0], [1, 2, 3, 1, 2, 3, 1])

    def test_stacked_rnn_layers_have_distinct_carries(self):
        l1 = LSTMModule(input_size=3, hidden_size=4, in_key="observation", out_key="e1")
        l2 = LSTMModule(input_size=4, hidden_size=4, in_key="e1", out_key="e2")
        assert set(l1._carry_keys()).isdisjoint(l2._carry_keys())
        # step mode: both layers carry independent state
        td = ArrayDict(observation=jax.random.normal(KEY, (2, 3)), is_init=jnp.ones((2,), bool))
        p1 = l1.init(KEY, td)
        with set_recurrent_mode("step"):
            td = l1._step(p1, td)
            p2 = l2.init(KEY, td)
            td = l2._step(p2, td)
        keys = [k for k in td["recurrent"].keys()]
        assert len(keys) == 4  # 2 carries per layer, no collisions
