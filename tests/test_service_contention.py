"""TCP-service contention tests (round-3 VERDICT weak: race detection —
'no contention tests for TCP services under load'; reference strategy:
test/test_distributed.py hammers services from many clients).

Many threads hit the line-JSON control plane and the replay service
concurrently; the invariants are linearizability-shaped: no lost updates,
no cross-talk between replies, consistent buffer size accounting.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.comm import TCPCommandClient, TCPCommandServer
from rl_tpu.data import ArrayDict
from rl_tpu.data.replay import DeviceStorage, ReplayBuffer
from rl_tpu.data.replay.service import RemoteReplayBuffer, ReplayService

N_THREADS = 16
N_CALLS = 25


class TestCommandServerContention:
    def test_counter_no_lost_updates(self):
        """N threads x M increments through the TCP endpoint: the handler
        guards its state with a lock; the total must be exact."""
        srv = TCPCommandServer(port=0)
        state = {"count": 0}
        lock = threading.Lock()

        def bump(_payload):
            with lock:
                state["count"] += 1
                return state["count"]

        srv.register_handler("bump", bump)
        srv.register_handler("echo", lambda p: p)
        srv.start()
        try:
            host, port = srv.address
            errors = []

            def worker(tid):
                c = TCPCommandClient(host, port)
                try:
                    for i in range(N_CALLS):
                        c.call("bump")
                        # interleaved echo: replies must not cross-talk
                        out = c.call("echo", {"tid": tid, "i": i})
                        assert out == {"tid": tid, "i": i}, out
                except Exception as e:  # noqa: BLE001 - collect for the assert
                    errors.append(e)

            ts = [threading.Thread(target=worker, args=(t,)) for t in range(N_THREADS)]
            [t.start() for t in ts]
            [t.join(timeout=60) for t in ts]
            assert not errors, errors
            assert state["count"] == N_THREADS * N_CALLS
        finally:
            srv.shutdown()

    def test_unknown_command_does_not_wedge_server(self):
        srv = TCPCommandServer(port=0)
        srv.register_handler("ok", lambda p: 1)
        srv.start()
        try:
            host, port = srv.address
            c = TCPCommandClient(host, port)
            with pytest.raises(Exception):
                c.call("nope")
            assert c.call("ok") == 1  # server still serves after the error
        finally:
            srv.shutdown()


class TestReplayServiceContention:
    def test_concurrent_extend_and_sample(self):
        """Writers extend while readers sample: the final size equals the
        sum of all extends (no lost writes) and every sampled batch has
        consistent shapes."""
        example = ArrayDict(
            observation=jnp.zeros((3,), jnp.float32),
            value=jnp.zeros((), jnp.float32),
        )
        service = ReplayService(
            ReplayBuffer(DeviceStorage(4096)), example, port=0
        ).start()
        try:
            host, port = service.address
            per_writer, rows = 10, 8
            errors = []

            def writer(tid):
                remote = RemoteReplayBuffer(host, port)
                try:
                    for i in range(per_writer):
                        batch = ArrayDict(
                            observation=jnp.full((rows, 3), float(tid)),
                            value=jnp.full((rows,), float(i)),
                        )
                        remote.extend(batch)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            def reader():
                remote = RemoteReplayBuffer(host, port)
                try:
                    for _ in range(per_writer):
                        if int(remote.size()) >= rows:
                            s = remote.sample(batch_size=4)
                            assert s["observation"].shape == (4, 3)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [
                threading.Thread(target=writer, args=(t,)) for t in range(8)
            ] + [threading.Thread(target=reader) for _ in range(4)]
            [t.start() for t in threads]
            [t.join(timeout=120) for t in threads]
            assert not errors, errors[:3]
            assert int(service.buffer.size(service.state)) == 8 * per_writer * rows
        finally:
            service.shutdown()

    def test_priority_updates_under_load(self):
        """Concurrent sample+update_priority cycles stay finite and the
        sampler state never corrupts (the PER state is swapped atomically
        under the service lock)."""
        example = ArrayDict(x=jnp.zeros((2,), jnp.float32))
        from rl_tpu.data import PrioritizedSampler

        service = ReplayService(
            ReplayBuffer(DeviceStorage(1024), PrioritizedSampler()),
            example,
            port=0,
        ).start()
        try:
            host, port = service.address
            seed = RemoteReplayBuffer(host, port)
            seed.extend(ArrayDict(x=jnp.ones((64, 2))))
            errors = []

            def cycle():
                remote = RemoteReplayBuffer(host, port)
                try:
                    for i in range(10):
                        s = remote.sample(batch_size=8)
                        idx = np.asarray(s["index"])
                        remote.update_priority(idx, np.abs(np.random.randn(8)) + 0.1)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            ts = [threading.Thread(target=cycle) for _ in range(8)]
            [t.start() for t in ts]
            [t.join(timeout=120) for t in ts]
            assert not errors, errors[:3]
            prio = np.asarray(service.state["sampler", "priorities"][:64])
            assert np.isfinite(prio).all() and (prio > 0).all()
        finally:
            service.shutdown()
