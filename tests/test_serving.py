"""Continuous batching + paged KV serving (round-4 VERDICT next-step #6;
reference: vLLM delegation in torchrl/modules/llm/backends/vllm/
vllm_async.py — continuous batching :515, paged KV, load balancing :1559).

Strategy: (1) the paged-attention cache path must be numerically
identical to the dense-cache path; (2) the engine must recycle blocks and
match fixed-batch greedy outputs; (3) at mixed sequence lengths the
engine must beat fixed-batch generate by >= 1.5x on decode work per
useful token (the continuous-batching claim, asserted on deterministic
work accounting; wall-clock is printed for reference)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.models import (
    ContinuousBatchingEngine,
    TransformerConfig,
    TransformerLM,
    generate,
)

KEY = jax.random.key(0)


def small_model(**kw):
    cfg = TransformerConfig(
        vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq_len=128, dtype=jnp.float32, **kw,
    )
    m = TransformerLM(cfg)
    params = m.init(KEY, jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


class TestPagedAttention:
    @pytest.mark.parametrize("gqa", [False, True])
    def test_prefill_and_decode_match_dense(self, gqa):
        m, params = small_model(n_kv_heads=2 if gqa else None)
        toks = jax.random.randint(KEY, (3, 10), 0, 97)
        S, block, nb, maxb = 3, 4, 16, 8
        cache = m.init_paged_cache(S, nb, block, maxb)
        table = np.full((S, maxb), -1, np.int32)
        for s in range(S):
            table[s, :4] = 1 + s * 4 + np.arange(4)
        for layer in cache:
            layer["block_table"] = jnp.asarray(table)
            layer["active"] = jnp.ones((S,), bool)
        lg, cache = m.apply({"params": params}, toks, cache=cache)
        ref = m.apply({"params": params}, toks)
        assert float(jnp.abs(lg - ref).max()) < 1e-3

        nxt = jax.random.randint(jax.random.key(1), (3, 5), 0, 97)
        full = jnp.concatenate([toks, nxt], axis=1)
        ref_full = m.apply({"params": params}, full)
        cur = cache
        for t in range(5):
            lgt, cur = m.apply({"params": params}, nxt[:, t : t + 1], cache=cur)
            err = float(jnp.abs(lgt[:, 0] - ref_full[:, 10 + t]).max())
            assert err < 1e-3, (t, err)

    def test_ragged_bucketed_prefill(self):
        """Token-level active masks: padded prompts of different lengths
        in ONE prefill call, each matching its unpadded oracle."""
        m, params = small_model()
        toks = jax.random.randint(KEY, (3, 10), 0, 97)
        lens = [4, 7, 10]
        S, block, nb, maxb = 3, 4, 16, 8
        cache = m.init_paged_cache(S, nb, block, maxb)
        table = np.full((S, maxb), -1, np.int32)
        for s in range(S):
            table[s, :4] = 1 + s * 4 + np.arange(4)
        for layer in cache:
            layer["block_table"] = jnp.asarray(table)
            layer["active"] = (
                jnp.arange(10)[None, :] < jnp.asarray(lens)[:, None]
            )
        lg, cache = m.apply({"params": params}, toks, cache=cache)
        for s, L in enumerate(lens):
            ref = m.apply({"params": params}, toks[s : s + 1, :L])
            assert float(jnp.abs(lg[s, :L] - ref[0]).max()) < 1e-3
            assert int(cache[0]["len"][s]) == L


class TestEngine:
    def test_drain_recycle_and_greedy_equivalence(self):
        m, params = small_model()
        eng = ContinuousBatchingEngine(
            m, params, n_slots=4, block_size=8, n_blocks=65,
            prompt_buckets=(16, 32), greedy=True,
        )
        rng = np.random.default_rng(0)
        rids = [
            eng.submit(rng.integers(0, 97, int(rng.integers(4, 20))),
                       int(rng.integers(4, 24)))
            for _ in range(10)
        ]
        out = eng.run()
        assert set(out) == set(rids)
        assert len(eng.free_blocks) == 64  # every block returned

        f0 = out[rids[0]]
        P = len(f0.prompt)
        g = generate(
            m, params, jnp.asarray(f0.prompt)[None], jnp.ones((1, P)),
            jax.random.key(9), max_new_tokens=len(f0.tokens), greedy=True,
            eos_id=None,
        )
        assert (f0.tokens == np.asarray(g.response_tokens[0])).all()

    def test_eos_frees_slot_early(self):
        m, params = small_model()
        eng = ContinuousBatchingEngine(
            m, params, n_slots=2, block_size=8, n_blocks=33,
            prompt_buckets=(16,), greedy=True, eos_id=None,
        )
        # find the greedy first token for a prompt, then rerun with that
        # token as eos: the request must finish in exactly 1 token
        rid = eng.submit(np.arange(5), 8)
        out = eng.run()
        first = int(out[rid].tokens[0])
        eng2 = ContinuousBatchingEngine(
            m, params, n_slots=2, block_size=8, n_blocks=33,
            prompt_buckets=(16,), greedy=True, eos_id=first,
        )
        rid2 = eng2.submit(np.arange(5), 8)
        out2 = eng2.run()
        assert out2[rid2].finished_reason == "eos"
        assert len(out2[rid2].tokens) == 1
        assert len(eng2.free_blocks) == 32

    def test_pool_too_small_raises(self):
        m, params = small_model()
        eng = ContinuousBatchingEngine(
            m, params, n_slots=2, block_size=8, n_blocks=2,  # 1 usable block
            prompt_buckets=(16,), greedy=True,
        )
        eng.submit(np.arange(12), 8)  # needs 2 blocks for prompt+1
        with pytest.raises(RuntimeError, match="block pool too small"):
            eng.run()


class TestThroughput:
    @pytest.mark.slow
    def test_continuous_beats_fixed_batch_at_mixed_lengths(self):
        """The headline claim: >= 1.5x less decode work per useful token
        than fixed batching when lengths vary (reference vLLM's win)."""
        m, params = small_model()
        S = 4
        # the vLLM scenario: mostly short responses with a heavy tail —
        # fixed batching runs every row to the batch max, so one long
        # request stalls its whole batch
        rng = np.random.default_rng(1)
        lengths = [8, 8, 12, 64] * 4
        reqs = [
            (rng.integers(0, 97, int(rng.integers(4, 16))), n)
            for n in lengths
        ]
        useful = sum(n for _, n in reqs)

        eng = ContinuousBatchingEngine(
            m, params, n_slots=S, block_size=8, n_blocks=129,
            prompt_buckets=(16,), greedy=True,
        )
        t0 = time.perf_counter()
        for p, n in reqs:
            eng.submit(p, n)
        out = eng.run()
        t_engine = time.perf_counter() - t0
        assert len(out) == len(reqs)
        engine_work = eng.decode_steps * S + eng.prefill_token_slots

        # fixed batching: groups of S in submission order; every row runs
        # to the batch max (what generate() computes), prompts padded to
        # the same bucket the engine uses
        fixed_work = 0
        t1 = time.perf_counter()
        for i in range(0, len(reqs), S):
            chunk = reqs[i : i + S]
            maxp = max(len(p) for p, _ in chunk)
            maxn = max(n for _, n in chunk)
            toks = np.zeros((len(chunk), maxp), np.int32)
            mask = np.zeros((len(chunk), maxp), np.float32)
            for j, (p, _) in enumerate(chunk):
                toks[j, maxp - len(p):] = p  # left-pad (generate convention)
                mask[j, maxp - len(p):] = 1.0
            generate(m, params, jnp.asarray(toks), jnp.asarray(mask),
                     jax.random.key(i), max_new_tokens=maxn, greedy=True,
                     eos_id=None)
            fixed_work += len(chunk) * (16 + maxn)  # bucketed prefill + decode
        t_fixed = time.perf_counter() - t1

        eff_engine = useful / engine_work
        eff_fixed = useful / fixed_work
        ratio = eff_engine / eff_fixed
        print(
            f"\nuseful={useful} engine_work={engine_work} fixed_work={fixed_work} "
            f"work-efficiency ratio={ratio:.2f}x | wall: engine={t_engine:.2f}s "
            f"fixed={t_fixed:.2f}s"
        )
        assert ratio >= 1.5, f"continuous batching only {ratio:.2f}x over fixed"


class TestAllocatorEdgeCases:
    def test_block_multiple_prompt_leaks_no_block(self):
        """P == block_size: the first decode growth must not overwrite the
        pre-allocated second block (round-5 review finding)."""
        m, params = small_model()
        eng = ContinuousBatchingEngine(
            m, params, n_slots=2, block_size=8, n_blocks=17,
            prompt_buckets=(16,), greedy=True,
        )
        for _ in range(3):  # several generations through the same pool
            rid = eng.submit(np.arange(8), 10)  # P exactly one block
            eng.run()
        assert len(eng.free_blocks) == 16  # nothing leaked

    def test_all_stalled_raises_not_livelock(self):
        m, params = small_model()
        eng = ContinuousBatchingEngine(
            m, params, n_slots=2, block_size=8, n_blocks=5,  # 4 usable
            prompt_buckets=(16,), greedy=True,
        )
        eng.submit(np.arange(7), 20)
        eng.submit(np.arange(7), 20)
        with pytest.raises(RuntimeError, match="stalled"):
            eng.run()

    def test_submit_validation(self):
        m, params = small_model()
        eng = ContinuousBatchingEngine(
            m, params, n_slots=2, block_size=8, n_blocks=17,
            prompt_buckets=(16,), greedy=True,
        )
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.arange(4), 0)
        with pytest.raises(ValueError, match="largest prefill bucket"):
            eng.submit(np.arange(40), 4)

    def test_paged_cache_rejects_attention_mask(self):
        m, params = small_model()
        cache = m.init_paged_cache(2, 8, 4, 4)
        toks = jnp.zeros((2, 4), jnp.int32)
        with pytest.raises(ValueError, match="paged cache path ignores"):
            m.apply({"params": params}, toks,
                    attention_mask=jnp.ones((2, 4), bool), cache=cache)


class TestPagedDecodeKernel:
    """Pallas paged-decode (interpret mode on CPU; reads the pool in
    place through the scalar-prefetched block table)."""

    def test_kernel_matches_oracle_ragged_gqa(self):
        from rl_tpu.ops.attention import paged_flash_decode

        S, H, Hk, D = 3, 4, 2, 16
        N, Bk, maxb = 12, 8, 4
        key = jax.random.key(0)
        pool_k = jax.random.normal(key, (N, Hk, Bk, D))  # head-major
        pool_v = jax.random.normal(jax.random.fold_in(key, 1), (N, Hk, Bk, D))
        table = np.full((S, maxb), -1, np.int32)
        lens = np.array([5, 16, 23], np.int32)
        for s_ in range(S):
            nb = -(-int(lens[s_]) // Bk)
            table[s_, :nb] = 1 + s_ * 3 + np.arange(nb)
        q = jax.random.normal(jax.random.fold_in(key, 2), (S, 1, H, D))
        out = paged_flash_decode(
            q, pool_k, pool_v, jnp.asarray(table), jnp.asarray(lens),
            interpret=True,
        )
        group = H // Hk
        for s_ in range(S):
            L = int(lens[s_])
            blocks = [b for b in table[s_] if b >= 0]
            # head-major pool: [N, Hk, Bk, D] -> per-head concat over blocks
            kf = np.concatenate([np.asarray(pool_k[b]) for b in blocks], 1)[:, :L]
            vf = np.concatenate([np.asarray(pool_v[b]) for b in blocks], 1)[:, :L]
            for h in range(H):
                kh, vh = kf[h // group], vf[h // group]
                sc = (np.asarray(q[s_, 0, h]) @ kh.T) * (D**-0.5)
                p = np.exp(sc - sc.max())
                p /= p.sum()
                np.testing.assert_allclose(
                    np.asarray(out[s_, 0, h]), p @ vh, rtol=1e-4, atol=1e-5
                )

    def test_model_decode_path_matches_xla_paged(self):
        """TransformerLM with flash_decode=True routes paged decode steps
        through the kernel; logits must match the XLA paged read."""
        cfg_kw = dict(
            vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=64, dtype=jnp.float32,
        )
        m_xla, params = small_model(n_kv_heads=2)
        from rl_tpu.models import TransformerConfig, TransformerLM

        m_krn = TransformerLM(TransformerConfig(
            flash_decode=True, flash_interpret=True,
            **{**cfg_kw, "max_seq_len": 128},
        ))
        toks = jax.random.randint(KEY, (2, 10), 0, 97)
        S, block, nb, maxb = 2, 4, 16, 8

        def fresh_cache(model):
            cache = model.init_paged_cache(S, nb, block, maxb)
            table = np.full((S, maxb), -1, np.int32)
            for s_ in range(S):
                table[s_, :4] = 1 + s_ * 4 + np.arange(4)
            for layer in cache:
                layer["block_table"] = jnp.asarray(table)
                layer["active"] = jnp.ones((S,), bool)
            return cache

        c1 = fresh_cache(m_xla)
        c2 = fresh_cache(m_krn)
        _, c1 = m_xla.apply({"params": params}, toks, cache=c1)  # XLA prefill
        _, c2 = m_krn.apply({"params": params}, toks, cache=c2)  # same (T>1)
        nxt = jax.random.randint(jax.random.key(1), (2, 3), 0, 97)
        for t in range(3):
            l1, c1 = m_xla.apply({"params": params}, nxt[:, t : t + 1], cache=c1)
            l2, c2 = m_krn.apply({"params": params}, nxt[:, t : t + 1], cache=c2)
            err = float(jnp.abs(l1 - l2).max())
            assert err < 1e-3, (t, err)


class TestLLMCollectorContinuousBatching:
    def test_grpo_batch_through_the_engine(self):
        """LLMCollector(continuous_batching=True) yields the same batch
        SCHEMA as the fixed-batch path, with behavior log-probs from the
        engine, early-eos rows masked, and the GRPO loss consuming it."""
        from rl_tpu.collectors.llm import LLMCollector
        from rl_tpu.envs.llm import DatasetChatEnv
        from rl_tpu.objectives.llm.grpo import GRPOLoss
        from rl_tpu.models import token_log_probs

        m, params = small_model()

        class TinyTok:
            eos_token_id = 1

            def encode(self, s):
                return [ord(c) % 90 + 2 for c in s][:12]

        from rl_tpu.data.llm import History

        prompts = History.from_chats([
            [{"role": "user", "content": p}]
            for p in ("what is 2+2?", "name a color", "count to three")
        ])
        env = DatasetChatEnv(
            prompts,
            TinyTok(),
            reward_fn=lambda h, toks: 0.5,
            group_repeats=2,
            max_prompt_len=16,
        )
        coll = LLMCollector(
            env, m, num_prompts=2, max_new_tokens=8, eos_id=1,
            continuous_batching=True, engine_slots=2,
        )
        batch = coll.collect(params, jax.random.key(0))
        G = batch["tokens"].shape[0]
        T = batch["tokens"].shape[1]
        for k in ("tokens", "attention_mask", "assistant_mask", "sample_log_prob"):
            assert batch[k].shape[:2] == (G, T), k
        assert batch["advantage"].shape == (G,)
        # behavior log-probs: where assistant_mask is on, they must be
        # real log-probs (<= 0, not the 0 padding)
        lp = np.asarray(batch["sample_log_prob"])
        am = np.asarray(batch["assistant_mask"])
        assert (lp[am] <= 0.0).all()
        assert (lp[am] < -1e-6).any()

        loss = GRPOLoss(lambda p, b: token_log_probs(m, p, b["tokens"]))
        v, metrics = loss(params, batch)
        assert np.isfinite(float(v))


class TestLoadBalancer:
    def _engines(self, n=3):
        m, params = small_model()
        from rl_tpu.models import ContinuousBatchingEngine

        return [
            ContinuousBatchingEngine(
                m, params, n_slots=2, block_size=8, n_blocks=33,
                prompt_buckets=(16,), greedy=True, seed=i,
            )
            for i in range(n)
        ]

    def test_requests_strategy_picks_least_loaded(self):
        from rl_tpu.models import LoadBalancer

        engines = self._engines()
        lb = LoadBalancer(engines, "requests")
        engines[0].submit(np.arange(4), 4)
        engines[0].submit(np.arange(4), 4)
        engines[1].submit(np.arange(4), 4)
        assert lb.select_engine() == 2

    def test_prefix_aware_is_sticky_and_respects_overload(self):
        from rl_tpu.models import LoadBalancer

        engines = self._engines()
        lb = LoadBalancer(engines, ["prefix-aware", "requests"])
        p = np.arange(10)
        first = lb.select_engine(p)
        assert all(lb.select_engine(p) == first for _ in range(5))  # sticky
        # overload the sticky replica far past threshold -> falls back
        for _ in range(8):
            engines[first].submit(np.arange(4), 2)
        assert lb.select_engine(p) != first

    def test_round_robin_cycles(self):
        from rl_tpu.models import LoadBalancer

        lb = LoadBalancer(self._engines(), "round-robin")
        assert [lb.select_engine() for _ in range(4)] == [0, 1, 2, 0]

    def test_submit_and_run_all_completes_everything(self):
        from rl_tpu.models import LoadBalancer

        engines = self._engines()
        lb = LoadBalancer(engines, ["prefix-aware", "requests"])
        rng = np.random.default_rng(0)
        keys = [
            lb.submit(rng.integers(0, 97, int(rng.integers(4, 12))),
                      int(rng.integers(2, 6)))
            for _ in range(9)
        ]
        out = lb.run_all()
        assert set(out) == set(keys)
        assert all(len(f.tokens) >= 1 for f in out.values())
        # every pool fully recycled on every replica
        assert all(len(e.free_blocks) == 32 for e in engines)

    def test_validation(self):
        from rl_tpu.models import LoadBalancer

        with pytest.raises(ValueError, match="at least one"):
            LoadBalancer([])
        with pytest.raises(ValueError, match="unknown strategy"):
            LoadBalancer(self._engines(1), "magic")

    def test_losing_last_engine_sheds_not_crashes(self):
        """ISSUE-6 satellite: runtime loss of the LAST engine surfaces
        ServiceSaturated/retry_after — a graceful shed the routing thread
        survives — not the constructor's ValueError (or a
        ZeroDivisionError from the mean-load math)."""
        from rl_tpu.models import LoadBalancer, ServiceSaturated

        lb = LoadBalancer(self._engines(1), "requests", retry_after_s=0.5)
        assert lb.select_engine() == 0
        lb.engines.clear()  # the fleet removed the last sick replica
        with pytest.raises(ServiceSaturated) as ei:
            lb.select_engine()
        assert ei.value.retry_after == 0.5
        with pytest.raises(ServiceSaturated):
            lb.submit(np.arange(4), 2)
        # an empty set is constructible when asked for (fleet startup)
        assert LoadBalancer([], allow_empty=True).engines == []


class TestChunkedDecode:
    def test_chunked_equals_single_step_greedy(self):
        m, params = small_model()
        rng = np.random.default_rng(0)
        reqs = [
            (rng.integers(0, 97, int(rng.integers(4, 16))),
             int(rng.integers(3, 20)))
            for _ in range(8)
        ]

        def run(chunk):
            eng = ContinuousBatchingEngine(
                m, params, n_slots=3, block_size=8, n_blocks=49,
                prompt_buckets=(16,), greedy=True, decode_chunk=chunk,
            )
            rids = [eng.submit(p, n) for p, n in reqs]
            out = eng.run()
            assert len(eng.free_blocks) == 48
            return {i: out[r].tokens.tolist() for i, r in enumerate(rids)}

        assert run(1) == run(4)

    def test_auto_chunk_equals_single_step_greedy(self):
        """decode_chunk="auto" (the measured tuner) must stay token-identical
        to single-step greedy — on a COLD engine (tuner at its init chunk)
        and on the same engine re-run warm (tuner possibly at a larger
        ladder rung, double-buffered drains in flight)."""
        m, params = small_model()
        rng = np.random.default_rng(3)
        reqs = [
            (rng.integers(0, 97, int(rng.integers(4, 16))),
             int(rng.integers(3, 20)))
            for _ in range(8)
        ]

        def run_fixed1():
            eng = ContinuousBatchingEngine(
                m, params, n_slots=3, block_size=8, n_blocks=49,
                prompt_buckets=(16,), greedy=True, decode_chunk=1,
            )
            rids = [eng.submit(p, n) for p, n in reqs]
            out = eng.run()
            return {i: out[r].tokens.tolist() for i, r in enumerate(rids)}

        ref = run_fixed1()
        eng = ContinuousBatchingEngine(
            m, params, n_slots=3, block_size=8, n_blocks=49,
            prompt_buckets=(16,), greedy=True, decode_chunk="auto",
        )
        for round_ in range(2):  # cold, then warm-tuner
            rids = [eng.submit(p, n) for p, n in reqs]
            out = eng.run()
            got = {i: out[r].tokens.tolist() for i, r in enumerate(rids)}
            assert got == ref, f"auto-chunk mismatch on round {round_}"
            assert len(eng.free_blocks) == 48

    def test_host_sync_bound_per_generated_token(self):
        """Host-sync regression guard: with decode_chunk=K the engine may
        block on at most one device->host transfer per K decode steps (one
        drain per chunk) plus one per admission round — NOT one per token,
        the round-5 loop's failure mode. At full slot occupancy that is
        <= 1/K transfers per generated token."""
        m, params = small_model()
        chunk, n, S = 4, 16, 4
        reqs = [(np.arange(6), n) for _ in range(2 * S)]  # uniform: slots stay full
        eng = ContinuousBatchingEngine(
            m, params, n_slots=S, block_size=8, n_blocks=S * 16 + 1,
            prompt_buckets=(16,), greedy=True, decode_chunk=chunk,
        )
        rids = [eng.submit(p, n_) for p, n_ in reqs]
        out = eng.run()
        gen = sum(len(out[r].tokens) for r in rids)
        assert gen == len(reqs) * n
        # every drain covers a whole chunk of decode steps
        assert eng.decode_drains * chunk == eng.decode_steps
        assert eng.decode_launches == eng.decode_drains
        # total blocking transfers (drains + admission syncs) stay under
        # one per chunk-of-generated-tokens
        assert eng.host_transfers <= gen / chunk

    def test_chunked_with_eos_discards_tail(self):
        m, params = small_model()
        # find the greedy continuation, then use its SECOND token as eos:
        # the chunked engine must stop at its FIRST occurrence even
        # mid-chunk (the greedy continuation may repeat a token, so the
        # expected cut is the first index of that value, not index 1)
        eng = ContinuousBatchingEngine(
            m, params, n_slots=1, block_size=8, n_blocks=17,
            prompt_buckets=(16,), greedy=True,
        )
        rid = eng.submit(np.arange(5), 8)
        ref = eng.run()[rid].tokens
        eos = int(ref[1])
        cut = ref.tolist().index(eos) + 1
        eng2 = ContinuousBatchingEngine(
            m, params, n_slots=1, block_size=8, n_blocks=17,
            prompt_buckets=(16,), greedy=True, eos_id=eos, decode_chunk=4,
        )
        rid2 = eng2.submit(np.arange(5), 8)
        out = eng2.run()[rid2]
        assert out.finished_reason == "eos"
        assert out.tokens.tolist() == ref[:cut].tolist()
        assert len(eng2.free_blocks) == 16


def test_chunked_decode_at_max_seq_len_boundary():
    """Round-5 review regression (verified crash): a sequence whose
    prompt + budget reaches max_seq_len must neither index past the
    block table nor corrupt the last block when decode_chunk speculates
    past the budget."""
    import jax.numpy as jnp

    from rl_tpu.models import ContinuousBatchingEngine, TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=97, d_model=32, n_layers=1, n_heads=2,
                            d_ff=64, max_seq_len=128, dtype=jnp.float32)
    m = TransformerLM(cfg)
    params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = np.arange(121) % 97

    def run(chunk):
        eng = ContinuousBatchingEngine(
            m, params, n_slots=1, block_size=8, n_blocks=33,
            prompt_buckets=(128,), greedy=True, decode_chunk=chunk,
        )
        rid = eng.submit(prompt, 7)  # 121 + 7 == max_seq_len exactly
        out = eng.run()[rid]
        assert len(eng.free_blocks) == 32
        return out.tokens.tolist()

    assert run(4) == run(1)
    assert len(run(4)) == 7


class TestServingService:
    def test_remote_submit_collect_matches_local_greedy(self):
        from rl_tpu.models import ContinuousBatchingEngine, RemoteEngine, ServingService

        m, params = small_model()

        def fresh():
            return ContinuousBatchingEngine(
                m, params, n_slots=2, block_size=8, n_blocks=33,
                prompt_buckets=(16,), greedy=True,
            )

        svc = ServingService(fresh()).start()
        try:
            host, port = svc.address
            client = RemoteEngine(host, port)
            rng = np.random.default_rng(0)
            reqs = [(rng.integers(0, 97, int(rng.integers(4, 12))),
                     int(rng.integers(2, 8))) for _ in range(6)]
            rids = [client.submit(p, n) for p, n in reqs]
            out = client.wait_all(rids)
            assert set(out) == set(rids)
            # greedy: remote tokens equal a local engine's for each prompt
            local = fresh()
            lr = [local.submit(p, n) for p, n in reqs]
            lout = local.run()
            for rid, (p, n), l in zip(rids, reqs, lr):
                assert out[rid]["tokens"] == lout[l].tokens.tolist()
            stats = client.stats()
            assert stats["pending"] == 0
            assert stats["free_blocks"] == 32
        finally:
            svc.shutdown()


def test_serving_service_metrics_endpoint_scrapes_prometheus_text():
    """PR-3 surface: GET /metrics on a running ServingService returns valid
    Prometheus text carrying KV-utilization, tokens/s, and queue-depth
    series, and the device-side token counter reflects the decode work
    actually done (drained once per launch, never per step)."""
    from urllib.request import urlopen

    from rl_tpu.models import ContinuousBatchingEngine, RemoteEngine, ServingService

    m, params = small_model()
    svc = ServingService(ContinuousBatchingEngine(
        m, params, n_slots=2, block_size=8, n_blocks=33,
        prompt_buckets=(16,), greedy=True,
    )).start()
    try:
        host, port = svc.address
        c = RemoteEngine(host, port)
        rids = [c.submit(np.arange(5), 4), c.submit(np.arange(7), 4)]
        c.wait_all(rids, timeout=60)
        mhost, mport = svc.metrics_address
        with urlopen(f"http://{mhost}:{mport}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        for series in (
            "rl_tpu_serving_tokens_total",
            "rl_tpu_serving_kv_utilization",
            "rl_tpu_serving_queue_depth",
            "rl_tpu_serving_tokens_per_second",
            'rl_tpu_serving_completions_total{reason="length"} 2',
        ):
            assert series in body, series
        tokens = [
            float(ln.split()[-1]) for ln in body.splitlines()
            if ln.startswith("rl_tpu_serving_tokens_total ")
        ][0]
        # 2 requests x 4 new tokens; prefill emits the first, decode the
        # other 3 each — the device counter counts decode tokens
        assert tokens == 6.0
    finally:
        svc.shutdown()


def test_serving_service_concurrent_waiters_keep_their_results():
    """collect(rids) takes only the named results; a second waiter's
    finished request must survive the first waiter's polling."""
    from rl_tpu.models import ContinuousBatchingEngine, RemoteEngine, ServingService

    m, params = small_model()
    svc = ServingService(ContinuousBatchingEngine(
        m, params, n_slots=2, block_size=8, n_blocks=33,
        prompt_buckets=(16,), greedy=True,
    )).start()
    try:
        host, port = svc.address
        c = RemoteEngine(host, port)
        r1 = c.submit(np.arange(5), 3)
        r2 = c.submit(np.arange(7), 3)
        out1 = c.wait_all([r1])  # polls collect([r1]) only
        assert set(out1) == {r1}
        out2 = c.wait_all([r2], timeout=30)  # r2 must still be there
        assert set(out2) == {r2}
    finally:
        svc.shutdown()
