"""Sharded experience tier tests (PR: GEAR-style partitioned replay).

Covers the two load-bearing claims of the tier:

1. **Distribution identity** — the two-stage draw (mixture over exact
   per-shard priority masses, then in-shard stratified sum-tree descent)
   is distribution-identical to one PER tree over the union when masses
   are fresh, with globally-normalized importance weights;
2. **Degradation, not failure** — a seeded mid-run shard crash renormalizes
   the mixture with ZERO learner-facing exceptions, and the Supervisor's
   keeper re-admits the restarted shard.

Plus the transport satellites: raw binary frames (+ base64 compat
fallback) and the ``{"saturated", "retry_after"}`` shed protocol.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import ArrayDict, DeviceStorage, PrioritizedSampler, ReplayBuffer
from rl_tpu.data.replay import (
    RemoteReplayBuffer,
    ReplaySaturated,
    ReplayService,
    ReplayShard,
    ShardedReplayBuffer,
)
from rl_tpu.data.replay.service import _decode_frames, _encode_frames
from rl_tpu.resilience.faults import Fault, FaultInjector, injection

KEY = jax.random.key(0)


def _example(obs_dim=4):
    return ArrayDict(
        observation=jnp.zeros((obs_dim,), jnp.float32),
        action=jnp.zeros((), jnp.int32),
    )


def _batch(n, obs_dim=4, fill=0.0):
    return ArrayDict(
        observation=jnp.full((n, obs_dim), fill, jnp.float32),
        action=jnp.arange(n, dtype=jnp.int32),
    )


def _service(cap=256, batch_size=16, **kw):
    buf = ReplayBuffer(DeviceStorage(cap), PrioritizedSampler(), batch_size=batch_size)
    return ReplayService(buf, _example(), seed=0, **kw).start()


# -- satellite: raw binary frames ---------------------------------------------


class TestBinaryWire:
    def test_frames_roundtrip_all_dtypes(self):
        td = ArrayDict(
            f32=jnp.asarray([[1.5, -2.0], [0.0, 3.25]], jnp.float32),
            i32=jnp.asarray([7, -1], jnp.int32),
            flag=jnp.asarray([True, False]),
            scalar=jnp.asarray(2.5, jnp.float32),
            nested=ArrayDict(x=jnp.arange(3, dtype=jnp.int32)),
        )
        meta, blob = _encode_frames(td)
        back = _decode_frames(meta, blob)
        for k in ("f32", "i32", "flag", "scalar"):
            np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(td[k]))
            assert back[k].dtype == td[k].dtype
        np.testing.assert_array_equal(
            np.asarray(back["nested", "x"]), np.asarray(td["nested", "x"])
        )

    def test_binary_extend_sample_roundtrip(self):
        svc = _service()
        try:
            rb = RemoteReplayBuffer(*svc.address)
            assert rb.extend(_batch(32)) == 32
            mb = rb.sample(16)
            assert mb["observation"].shape == (16, 4)
            assert "index" in mb and "_weight" in mb
            # shards export the sampled leaves' p^alpha for GLOBAL weight
            # recomputation at the coordinator
            assert "_p_alpha" in mb
            assert rb._binary  # never fell back
        finally:
            svc.shutdown()

    def test_legacy_fallback_when_peer_lacks_binary(self):
        svc = _service()
        # an old peer: binary handlers absent
        del svc.server._server._handlers["extend_bin"]
        del svc.server._server._handlers["sample_bin"]
        try:
            rb = RemoteReplayBuffer(*svc.address)
            assert rb.extend(_batch(32)) == 32
            assert not rb._binary  # flipped to base64 for good
            mb = rb.sample(8)
            assert mb["observation"].shape == (8, 4)
        finally:
            svc.shutdown()


# -- satellite: shed protocol ---------------------------------------------------


class TestShedProtocol:
    def test_saturated_raises_after_budget(self):
        svc = _service(max_inflight=0, retry_after_s=0.005)
        try:
            rb = RemoteReplayBuffer(*svc.address, max_shed_retries=2)
            with pytest.raises(ReplaySaturated) as ei:
                rb.extend(_batch(8))
            assert ei.value.retry_after == pytest.approx(0.005)
            with pytest.raises(ReplaySaturated):
                rb.sample(4)
        finally:
            svc.shutdown()

    def test_resubmit_succeeds_when_saturation_clears(self):
        svc = _service(max_inflight=0, retry_after_s=0.02)
        try:
            rb = RemoteReplayBuffer(*svc.address, max_shed_retries=20)
            t = threading.Timer(0.1, lambda: setattr(svc, "max_inflight", None))
            t.start()
            try:
                assert rb.extend(_batch(8)) == 8  # sheds, then lands
            finally:
                t.join()
        finally:
            svc.shutdown()


# -- tentpole: distribution identity -------------------------------------------


class TestShardedDistributionParity:
    def test_two_stage_matches_single_tree(self):
        """Fill 3 shards with known priorities; the coordinator's empirical
        sampling frequencies must match BOTH the analytic PER distribution
        p_i^alpha / M over the union AND a single device tree holding the
        same union — and the mixture itself must be exact."""
        n_shards, cap, alpha, beta = 3, 64, 0.6, 0.4
        n_total = n_shards * cap
        rng = np.random.default_rng(11)
        prios = rng.uniform(0.1, 4.0, n_total).astype(np.float32)
        pa = (np.abs(prios) + 1e-8) ** alpha
        exact = pa / pa.sum()

        def bf():
            return ReplayBuffer(
                DeviceStorage(cap),
                PrioritizedSampler(alpha=alpha, beta=beta),
                batch_size=64,
            )

        shards = [ReplayShard(i, bf, _example(), seed=i).start() for i in range(3)]
        coord = ShardedReplayBuffer(
            [s.address for s in shards], cap,
            batch_size=64, beta=beta, seed=5,
        )
        try:
            for i, s in enumerate(shards):
                c = RemoteReplayBuffer(*s.address)
                c.extend(_batch(cap, fill=float(i)))
                c.update_priority(np.arange(cap), prios[i * cap:(i + 1) * cap])
            coord.refresh_masses()

            # stage-1 exactness: the mixture IS the per-shard mass fractions
            shard_mass = pa.reshape(n_shards, cap).sum(axis=1)
            probs = coord.mixture_probs()
            for i in range(n_shards):
                assert probs[i] == pytest.approx(
                    shard_mass[i] / pa.sum(), rel=1e-4
                )

            counts = np.zeros(n_total)
            draws, B = 96, 64
            for _ in range(draws):
                mb = coord.sample(B)
                counts += np.bincount(
                    np.asarray(mb["index"]).ravel(), minlength=n_total
                )
            emp = counts / counts.sum()

            # single tree over the union, same alpha
            dev = PrioritizedSampler(alpha=alpha, beta=beta)
            st = dev.init(n_total)
            st = dev.on_write(st, jnp.arange(n_total), None)
            st = dev.update_priority(
                st, jnp.arange(n_total), jnp.asarray(prios), indices_sorted=True
            )
            counts_1 = np.zeros(n_total)
            samp = jax.jit(
                lambda st, k: dev.sample(st, k, B, jnp.asarray(n_total), n_total)
            )
            for i in range(draws):
                idx, _info, st = samp(st, jax.random.fold_in(KEY, i))
                counts_1 += np.bincount(np.asarray(idx), minlength=n_total)
            emp_1 = counts_1 / counts_1.sum()

            # L1 tolerances sized for 6144 draws over 192 cells
            assert np.abs(emp - exact).sum() < 0.15, np.abs(emp - exact).sum()
            assert np.abs(emp - emp_1).sum() < 0.2, np.abs(emp - emp_1).sum()
        finally:
            coord.close()
            for s in shards:
                s.shutdown()

    def test_global_importance_weights(self):
        """Coordinator weights must be (N_tot · p_i / M_tot)^-beta normalized
        by the GLOBAL batch max — not the shard-local max the shards reply
        with."""
        cap, alpha, beta = 32, 0.7, 0.5
        rng = np.random.default_rng(3)
        prios = rng.uniform(0.1, 5.0, 2 * cap).astype(np.float32)
        pa = (np.abs(prios) + 1e-8) ** alpha

        def bf():
            return ReplayBuffer(
                DeviceStorage(cap),
                PrioritizedSampler(alpha=alpha, beta=beta),
                batch_size=32,
            )

        shards = [ReplayShard(i, bf, _example(), seed=i).start() for i in range(2)]
        coord = ShardedReplayBuffer(
            [s.address for s in shards], cap, batch_size=32, beta=beta, seed=7,
        )
        try:
            for i, s in enumerate(shards):
                c = RemoteReplayBuffer(*s.address)
                c.extend(_batch(cap))
                c.update_priority(np.arange(cap), prios[i * cap:(i + 1) * cap])
            coord.refresh_masses()
            mb = coord.sample(32)
            idx = np.asarray(mb["index"]).ravel()
            expect = (2 * cap * pa[idx] / pa.sum()) ** (-beta)
            expect = expect / expect.max()
            np.testing.assert_allclose(
                np.asarray(mb["_weight"]), expect, rtol=2e-3
            )
        finally:
            coord.close()
            for s in shards:
                s.shutdown()

    def test_priority_update_routes_to_owning_shard(self):
        cap = 64

        def bf():
            return ReplayBuffer(
                DeviceStorage(cap), PrioritizedSampler(), batch_size=16
            )

        shards = [ReplayShard(i, bf, _example(), seed=i).start() for i in range(2)]
        coord = ShardedReplayBuffer(
            [s.address for s in shards], cap, batch_size=16, seed=0,
        )
        try:
            coord.extend(_batch(cap))
            coord.extend(_batch(cap))
            coord.refresh_masses()
            before = coord.mixture_probs()
            # boost shard 1's leaves through the GLOBAL index encoding
            coord.update_priority(
                cap + np.arange(cap), np.full(cap, 50.0, np.float32)
            )
            coord.refresh_masses()
            after = coord.mixture_probs()
            assert after[1] > 0.9 > before[1]
        finally:
            coord.close()
            for s in shards:
                s.shutdown()


# -- tentpole: chaos degradation ------------------------------------------------


class _ShardFleet:
    """3 shards + coordinator wired for restarts, torn down reliably."""

    def __init__(self, cap=256, batch_size=16, probe_interval_s=0.05):
        def bf():
            return ReplayBuffer(
                DeviceStorage(cap), PrioritizedSampler(), batch_size=batch_size
            )

        self.shards = [
            ReplayShard(i, bf, _example(), seed=i).start() for i in range(3)
        ]
        self.coord = ShardedReplayBuffer(
            [s.address for s in self.shards], cap,
            batch_size=batch_size, seed=0,
            mass_refresh_s=0.05,
            probe_interval_s=probe_interval_s,
            restart_fn=lambda i: self.shards[i].restart(),
        )

    def close(self):
        self.coord.close()
        for s in self.shards:
            try:
                s.shutdown()
            except Exception:
                pass


class TestChaosDegradation:
    def test_seeded_crash_degrades_then_readmits(self):
        """The acceptance chaos scenario: a seeded crash kills shard 1
        mid-run; the learner-facing loop sees ZERO exceptions, the mixture
        renormalizes over the survivors, and the Supervisor's keeper
        restart re-admits the shard."""
        fleet = _ShardFleet()
        coord, shards = fleet.coord, fleet.shards
        inj = FaultInjector(
            {"replay.shard_crash.1": Fault(kind="crash", at=(12,))}, seed=0
        )
        try:
            coord.start_keepers()
            errors = []
            failovers_before = coord._c_failover.value({"shard": "1"})
            readmits_before = coord._c_readmit.value({"shard": "1"})
            with injection(inj):
                for step in range(60):
                    try:
                        coord.extend(_batch(16, fill=float(step)))
                        if coord.size() >= 16:
                            mb = coord.sample(16)
                            assert mb["observation"].shape == (16, 4)
                            coord.update_priority(
                                np.asarray(mb["index"]),
                                np.full(16, 1.0, np.float32),
                            )
                    except Exception as e:  # noqa: BLE001 - the assertion IS "none"
                        errors.append(e)
                    time.sleep(0.005)
            assert errors == [], errors
            assert any(s == "replay.shard_crash.1" for s, _k, _n in inj.fired)
            # the failover counter records the transition durably — polling
            # alive_shards() can miss it when the keeper re-admits within
            # one loop tick
            assert coord._c_failover.value({"shard": "1"}) > failovers_before, (
                "shard 1 never left the mixture"
            )
            # keeper + supervisor re-admission
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if 1 in coord.alive_shards():
                    break
                time.sleep(0.02)
            assert 1 in coord.alive_shards(), "shard 1 never re-admitted"
            assert coord._c_readmit.value({"shard": "1"}) > readmits_before
            # the restarted shard takes traffic again
            coord.refresh_masses()
            for step in range(6):
                coord.extend(_batch(16))
            coord.refresh_masses()
            assert coord.mixture_probs()[1] > 0.0
        finally:
            fleet.close()

    def test_mixture_renormalizes_while_degraded(self):
        """While a shard is down the surviving masses renormalize to 1 and
        sampling draws only from survivors."""
        fleet = _ShardFleet()
        coord, shards = fleet.coord, fleet.shards
        inj = FaultInjector(
            {"replay.shard_crash.2": Fault(kind="crash", at=(1,))}, seed=0
        )
        try:
            for _ in range(6):
                coord.extend(_batch(32))
            coord.refresh_masses()
            with injection(inj):
                # first touch of shard 2 crashes it; NO keepers running, so
                # it stays out — the degraded steady state
                try:
                    coord.refresh_masses()
                except Exception:  # noqa: BLE001
                    pass
                coord.refresh_masses()
            assert coord.alive_shards() == [0, 1]
            probs = coord.mixture_probs()
            assert sum(probs.values()) == pytest.approx(1.0)
            assert set(probs) == {0, 1}
            cap = coord.shard_capacity
            for _ in range(4):
                mb = coord.sample(16)
                owners = np.asarray(mb["index"]).ravel() // cap
                assert set(owners.tolist()) <= {0, 1}
        finally:
            fleet.close()

    def test_link_drop_readmits_without_restart(self):
        """``replay.shard_drop`` severs one call; the keeper's probe finds
        the endpoint alive and re-admits WITHOUT rebuilding the shard (its
        experience survives — unlike a crash)."""
        restarts = []
        fleet = _ShardFleet(probe_interval_s=0.03)
        coord = fleet.coord
        coord._restart_fn = lambda i: (restarts.append(i), fleet.shards[i].restart())[1]
        inj = FaultInjector(
            {"replay.shard_drop": Fault(kind="drop", at=(2,))}, seed=0
        )
        try:
            for _ in range(3):
                coord.extend(_batch(32))
            size_before = coord.size()
            coord.start_keepers()
            with injection(inj):
                errors = []
                for step in range(30):
                    try:
                        coord.extend(_batch(8, fill=float(step)))
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                    time.sleep(0.005)
            assert errors == []
            assert any(s == "replay.shard_drop" for s, _k, _n in inj.fired)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if len(coord.alive_shards()) == 3:
                    break
                time.sleep(0.02)
            assert len(coord.alive_shards()) == 3
            assert restarts == []  # drop != crash: no rebuild
            assert coord.size() >= size_before  # experience survived
        finally:
            fleet.close()


# -- trainer drop-in -------------------------------------------------------------


class TestTrainerHostSource:
    def test_async_trainer_trains_through_sharded_buffer(self):
        """AsyncOffPolicyTrainer accepts the sharded buffer as a drop-in
        source: host-batch update programs run, priorities route back, the
        experience lands spread across shards, losses stay finite."""
        from tests.test_async_offpolicy import _HostEnv, _make_sac
        from rl_tpu.collectors import AsyncHostCollector, ThreadedEnvPool
        from rl_tpu.trainers import AsyncOffPolicyTrainer, OffPolicyConfig

        sac = _make_sac()
        pool = ThreadedEnvPool([lambda i=i: _HostEnv(seed=i) for i in range(2)])

        def policy(params, td, key):
            return sac.actor(params["actor"], td, key)

        coll = AsyncHostCollector(pool, policy, frames_per_batch=32, seed=0)
        cfg = OffPolicyConfig(
            batch_size=32, utd_ratio=1, learning_rate=3e-3, init_random_frames=32
        )
        cap = 512

        probe = AsyncOffPolicyTrainer.__new__(AsyncOffPolicyTrainer)
        probe.collector = coll
        example = AsyncOffPolicyTrainer.example_item(probe)

        def bf():
            return ReplayBuffer(
                DeviceStorage(cap), PrioritizedSampler(), batch_size=32
            )

        shards = [ReplayShard(i, bf, example, seed=i).start() for i in range(2)]
        coord = ShardedReplayBuffer(
            [s.address for s in shards], cap, batch_size=32, seed=0
        )
        tr = AsyncOffPolicyTrainer(coll, sac, coord, cfg, priority_key="td_error")
        assert tr._host_source
        ts = tr.init(jax.random.key(1))
        assert "buffer" not in ts  # replay state lives in the shards
        losses = []
        try:
            for ts, m in tr.train(ts, total_frames=160):
                if m is not None:
                    losses.append(float(m["loss_qvalue"]))
        finally:
            pool.close()
            coord.close()
            for s in shards:
                s.shutdown()
        assert len(losses) >= 3
        assert np.isfinite(losses).all()
