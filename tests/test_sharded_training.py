"""Pod-scale sharded RLHF on the virtual 8-device CPU mesh.

The PR-7 invariants:

- ``make_fsdp_mesh``/``fsdp_sharding``/``data_sharding`` implement the
  ``(batch, fsdp)`` layout: params shard their largest divisible dim over
  ``fsdp`` (min-size cutoff, replicated fallback), rollout batches shard
  their leading dim over both axes;
- the FSDP-sharded donated GRPO update is NUMERICALLY the single-device
  update (same seed, same collected batch → loss/param maxdiff bound);
- the weight-sync path moves only each device's shard: the push/pull
  cycle stays inside ``jax.transfer_guard("disallow")`` and no pulled
  leaf ever costs a full-replica gather;
- ``shard_train_state`` covers optimizer state and PRNG keys, and the
  off-policy program runs jitted on the FSDP mesh from those placements.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.mesh
from jax.sharding import PartitionSpec as P

from rl_tpu.envs.llm import arithmetic_dataset
from rl_tpu.obs import DeviceMetrics
from rl_tpu.parallel import (
    AXIS_FSDP,
    data_sharding,
    fsdp_sharding,
    make_fsdp_mesh,
    make_mesh,
    replicated,
    shard_train_state,
)
from rl_tpu.trainers.grpo import GRPOTrainer, PipelinedGRPOTrainer
from rl_tpu.weight_update import ShardedSyncScheme

KEY = jax.random.key(0)
N_DEV = 8


def _tiny(cls=GRPOTrainer, **kw):
    ds = arithmetic_dataset(n=64, max_operand=2)
    defaults = dict(num_prompts=4, group_repeats=4, max_prompt_len=8,
                    max_new_tokens=4, learning_rate=3e-3, kl_coeff=0.005)
    defaults.update(kw)
    return cls(ds, **defaults)


class TestFsdpMesh:
    def test_absorb_and_axis_order(self):
        mesh = make_fsdp_mesh(fsdp=4)
        assert mesh.shape["batch"] == 2 and mesh.shape["fsdp"] == 4
        assert mesh.axis_names == ("batch", "fsdp")

    def test_degenerate_data_parallel(self):
        mesh = make_fsdp_mesh(fsdp=1)
        assert mesh.shape["batch"] == N_DEV

    def test_validation(self):
        with pytest.raises(ValueError):
            make_fsdp_mesh(fsdp=0)
        with pytest.raises(ValueError):
            make_fsdp_mesh(fsdp=3)  # 8 % 3
        with pytest.raises(ValueError):
            make_fsdp_mesh(fsdp=4, batch=4)  # 16 > 8 devices


class TestFsdpSharding:
    def test_leaf_rules(self):
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        tree = {
            "w": jnp.ones((16, 8)),       # largest divisible dim -> dim0
            "tall": jnp.ones((3, 64)),    # dim0 indivisible -> dim1
            "odd": jnp.ones((7, 5)),      # no divisible dim -> replicated
            "scalar": jnp.float32(1.0),   # -> replicated
            "key": jax.random.key(0),     # PRNG -> replicated
        }
        sh = fsdp_sharding(tree, mesh, min_size_mbytes=0.0)
        assert sh["w"].spec == P(AXIS_FSDP, None)
        assert sh["tall"].spec == P(None, AXIS_FSDP)
        assert sh["odd"].spec == P()
        assert sh["scalar"].spec == P()
        assert sh["key"].spec == P()

    def test_min_size_cutoff_replicates_small_leaves(self):
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        small = {"w": jnp.ones((16, 8))}  # 512 B << 4 MB default cutoff
        assert fsdp_sharding(small, mesh)["w"].spec == P()
        big = {"w": jnp.ones((1024, 1536))}  # 6 MB
        assert fsdp_sharding(big, mesh)["w"].spec == P(None, AXIS_FSDP)

    def test_no_fsdp_axis_replicates(self):
        mesh = make_mesh()  # classic (data, context, expert, model)
        sh = fsdp_sharding({"w": jnp.ones((16, 8))}, mesh, min_size_mbytes=0.0)
        assert sh["w"].spec == P()

    def test_data_sharding_axes(self):
        assert data_sharding(make_fsdp_mesh(fsdp=4)).spec == P(("batch", "fsdp"))
        assert data_sharding(make_mesh()).spec == P(("data",))


class TestShardTrainState:
    def test_covers_opt_state_and_prng(self):
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        ts = {
            "params": {"w": jnp.ones((16, 8))},
            "opt": {"mu": jnp.ones((16, 8)), "count": jnp.int32(0)},
            "collector": {"obs": jnp.ones((8, 3)), "rng": jax.random.key(2)},
            "rng": jax.random.key(1),
            "update_count": jnp.int32(0),
        }
        out = shard_train_state(ts, mesh, num_envs=8, min_size_mbytes=0.0)
        assert out["params"]["w"].sharding.spec == P(AXIS_FSDP, None)
        assert out["opt"]["mu"].sharding.spec == P(AXIS_FSDP, None)
        assert out["opt"]["count"].sharding.is_fully_replicated
        # env state splits over BOTH data axes; PRNG keys always replicate
        assert out["collector"]["obs"].sharding.spec == P(("batch", "fsdp"))
        assert out["collector"]["rng"].sharding.is_fully_replicated
        assert out["rng"].sharding.is_fully_replicated

    def test_classic_mesh_unchanged(self):
        mesh = make_mesh()
        ts = {"params": {"w": jnp.ones((16, 8))},
              "collector": {"obs": jnp.ones((8, 3))}, "rng": jax.random.key(1)}
        out = shard_train_state(ts, mesh, num_envs=8)
        assert out["params"]["w"].sharding.is_fully_replicated
        assert out["collector"]["obs"].sharding.spec == P("data")

    def test_offpolicy_program_shard_state_runs_on_fsdp_mesh(self):
        from rl_tpu.collectors import Collector
        from rl_tpu.data import DeviceStorage, ReplayBuffer
        from rl_tpu.envs import CartPoleEnv, VmapEnv
        from rl_tpu.modules import MLP, TDModule
        from rl_tpu.objectives import DQNLoss
        from rl_tpu.trainers import OffPolicyConfig, OffPolicyProgram

        mesh = make_fsdp_mesh(fsdp=2, batch=4)
        num_envs = 8
        env = VmapEnv(CartPoleEnv(), num_envs)
        qnet = TDModule(MLP(out_features=2), ["observation"], ["action_value"])
        loss = DQNLoss(qnet, gamma=0.99)

        def policy(params, td, key):
            q = qnet(params["qvalue"], td)["action_value"]
            return td.set("action", jnp.argmax(q, axis=-1))

        coll = Collector(env, policy, frames_per_batch=64)
        program = OffPolicyProgram(
            coll, loss, ReplayBuffer(DeviceStorage(4096)),
            OffPolicyConfig(batch_size=32, utd_ratio=1),
        )
        ts = program.init(KEY)
        ts = program.shard_state(ts, mesh, min_size_mb=0.0)
        assert any(
            not x.sharding.is_fully_replicated
            for x in jax.tree.leaves(ts["params"])
        )
        with mesh:
            ts2, m = jax.jit(program.train_step)(ts)
        assert np.isfinite(float(m["loss"]))


class TestShardedSyncScheme:
    def test_versioned_pull_and_guard(self):
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        params = {"w": jnp.ones((16, 8)), "b": jnp.ones((8,))}
        sh = fsdp_sharding(params, mesh, min_size_mbytes=0.0)
        placed = jax.tree.map(jax.device_put, params, sh)
        scheme = ShardedSyncScheme(sh)
        with pytest.raises(RuntimeError):
            scheme.pull()
        # the whole publish/consume cycle is device-side only
        with jax.transfer_guard("disallow"):
            scheme.push(placed)
            p1, v1 = scheme.pull_versioned()
            scheme.push(p1)
            p2, v2 = scheme.pull_versioned()
        assert (v1, v2) == (1, 2)
        assert not p2["w"].sharding.is_fully_replicated

    def test_single_sharding_broadcasts(self):
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        scheme = ShardedSyncScheme(replicated(mesh))
        scheme.push({"w": jnp.ones((4, 4)), "b": jnp.ones((2,))})
        assert scheme.pull()["w"].sharding.is_fully_replicated


class TestShardedGRPO:
    def test_batch_divisibility_validated(self):
        with pytest.raises(ValueError):
            _tiny(mesh=make_fsdp_mesh(fsdp=4, batch=2),
                  num_prompts=3, group_repeats=2)  # B=6, extent 8

    def test_update_parity_vs_single_device(self):
        """Same seed, same collected batch: the FSDP-sharded donated
        update must produce the single-device loss and params to within
        reduction-reorder noise."""
        t0 = _tiny()
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        t1 = _tiny(mesh=mesh, fsdp_min_size_mb=0.0)
        for a, b in zip(jax.tree.leaves(t0.params), jax.tree.leaves(t1.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        t0._key, k = jax.random.split(t0._key)
        batch = t0.collector.collect(None, k)
        p0, o0, dm0 = t0._update(t0.params, t0.opt_state, batch, t0._dm)
        b1 = jax.device_put(batch, t1._batch_placement)
        p1, o1, dm1 = t1._update(
            t1.params, t1.opt_state, b1, t1._dm, t1._poison_zero
        )
        maxdiff = max(
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1))
        )
        # Adam's first-step normalization (m/(sqrt(v)+eps) with v ~ g^2)
        # amplifies f32 reduction-reorder noise toward O(lr); observed
        # ~0.06*lr, so lr/3 is 5x headroom while a real bug (dropped
        # microbatch, wrong advantage shard) lands at O(lr) or worse.
        assert maxdiff < 1e-3, f"sharded update diverged: maxdiff={maxdiff}"
        l0 = float(t0._dm_spec.to_flat(DeviceMetrics.drain(dm0))["loss"])
        l1 = float(t1._dm_spec.to_flat(DeviceMetrics.drain(dm1))["loss"])
        assert abs(l0 - l1) < 1e-5

    def test_fsdp_trainer_steps_and_params_stay_sharded(self):
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        t = _tiny(mesh=mesh, fsdp_min_size_mb=0.0)
        assert isinstance(t.scheme, ShardedSyncScheme)
        for _ in range(2):
            m = t.step()
            assert np.isfinite(m["loss"])
        assert any(
            not x.sharding.is_fully_replicated for x in jax.tree.leaves(t.params)
        )
        assert any(
            not x.sharding.is_fully_replicated
            for x in jax.tree.leaves(t.opt_state)
        )

    def test_sync_path_moves_only_shards(self):
        """The acceptance bound: weight sync transfers per-device shards
        only. (a) the push/pull cycle runs under
        ``jax.transfer_guard("disallow")`` — nothing crosses the host
        boundary; (b) every FSDP-sharded leaf's total addressable bytes
        equal global_bytes x batch_axis (replication over the batch axis
        only) — a full-replica gather would cost global_bytes x n_devices."""
        mesh = make_fsdp_mesh(fsdp=4, batch=2)
        t = _tiny(mesh=mesh, fsdp_min_size_mb=0.0)
        with jax.transfer_guard("disallow"):
            t.scheme.push(t.params)
            pulled, _ = t.scheme.pull_versioned()
        n_batch = mesh.shape["batch"]
        sharded = [
            x for x in jax.tree.leaves(pulled)
            if not x.sharding.is_fully_replicated
        ]
        assert sharded, "no leaf is FSDP-sharded at min_size=0"
        for x in sharded:
            total = sum(s.data.nbytes for s in x.addressable_shards)
            assert total == x.nbytes * n_batch
            assert total < x.nbytes * N_DEV
