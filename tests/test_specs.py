"""Spec-family tests (modeled on reference test/test_specs.py coverage:
rand/zero/is_in/project round-trips per spec type, composite nesting)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import (
    ArrayDict,
    Binary,
    Bounded,
    Categorical,
    Composite,
    MultiCategorical,
    MultiOneHot,
    NonTensor,
    OneHot,
    Unbounded,
    make_composite_from_arraydict,
    stack_specs,
)

KEY = jax.random.key(0)

LEAF_SPECS = [
    Bounded(shape=(3,), low=-1.0, high=2.0),
    Bounded(shape=(2, 2), low=0, high=5, dtype=jnp.int32),
    Unbounded(shape=(4,)),
    Unbounded(shape=(), dtype=jnp.int32),
    Categorical(n=7),
    Categorical(shape=(3,), n=4),
    MultiCategorical(nvec=(3, 4, 5)),
    OneHot(n=6),
    MultiOneHot(nvec=(2, 3)),
    Binary(shape=(5,)),
]


@pytest.mark.parametrize("spec", LEAF_SPECS, ids=lambda s: type(s).__name__ + str(s.shape))
class TestLeafProtocol:
    @pytest.mark.slow
    def test_rand_is_in(self, spec):
        x = spec.rand(KEY)
        assert spec.is_in(x), f"{spec} rejected own rand sample {x}"

    @pytest.mark.slow
    def test_rand_batched(self, spec):
        x = spec.rand(KEY, (10,))
        assert x.shape == (10, *spec.shape)
        assert spec.is_in(x)

    def test_zero_is_in(self, spec):
        z = spec.zero((2,))
        assert z.shape == (2, *spec.shape)

    @pytest.mark.slow
    def test_project_idempotent(self, spec):
        x = spec.rand(KEY, (4,))
        np.testing.assert_array_equal(spec.project(x), x)

    def test_to_sds(self, spec):
        sds = spec.to_sds((8,))
        assert sds.shape == (8, *spec.shape)
        assert sds.dtype == jnp.dtype(spec.dtype)

    def test_expand(self, spec):
        e = spec.expand(6)
        assert e.shape == (6, *spec.shape)


class TestDomains:
    def test_bounded_rejects_oob(self):
        spec = Bounded(shape=(2,), low=0.0, high=1.0)
        assert not spec.is_in(jnp.array([0.5, 1.5]))
        np.testing.assert_allclose(spec.project(jnp.array([-1.0, 2.0])), [0.0, 1.0])

    def test_bounded_int_rand_covers_range(self):
        spec = Bounded(shape=(100,), low=0, high=3, dtype=jnp.int32)
        x = spec.rand(KEY)
        assert set(np.unique(np.asarray(x))) <= {0, 1, 2, 3}
        assert x.max() == 3  # high is inclusive for ints

    def test_categorical_rejects(self):
        spec = Categorical(n=3)
        assert not spec.is_in(jnp.array(5, jnp.int32))
        assert spec.is_in(jnp.array(2, jnp.int32))
        assert spec.project(jnp.array(5, jnp.int32)) == 2

    def test_onehot_encode_project(self):
        spec = OneHot(n=4)
        enc = spec.encode(jnp.array(2))
        np.testing.assert_array_equal(enc, [0, 0, 1, 0])
        assert spec.is_in(enc)
        assert not spec.is_in(jnp.array([1.0, 1.0, 0.0, 0.0]))
        proj = spec.project(jnp.array([0.1, 0.9, 0.3, 0.2]))
        np.testing.assert_array_equal(proj, [0, 1, 0, 0])

    def test_onehot_to_categorical(self):
        assert OneHot(n=4).to_categorical_spec() == Categorical(shape=(), n=4)

    def test_multionehot_blocks(self):
        spec = MultiOneHot(nvec=(2, 3))
        x = spec.rand(KEY)
        assert x.shape == (5,)
        assert spec.is_in(x)
        assert not spec.is_in(jnp.ones(5))

    def test_multicategorical(self):
        spec = MultiCategorical(nvec=(3, 4))
        x = spec.rand(KEY, (50,))
        assert spec.is_in(x)
        assert not spec.is_in(jnp.full((2,), 9, jnp.int32))

    def test_binary(self):
        spec = Binary(shape=(3,), dtype=jnp.int32)
        assert spec.is_in(jnp.array([0, 1, 0], jnp.int32))
        assert not spec.is_in(jnp.array([0, 2, 0], jnp.int32))

    def test_nontensor(self):
        spec = NonTensor(example="hello")
        assert spec.rand(KEY) == "hello"
        assert spec.is_in("anything")
        assert spec.to_sds() is None


class TestComposite:
    def make(self):
        return Composite(
            observation=Bounded(shape=(3,), low=-1, high=1),
            action=Categorical(n=4),
            nested=Composite(x=Unbounded(shape=(2,))),
        )

    def test_rand_zero_is_in(self):
        spec = self.make()
        td = spec.rand(KEY, (5,))
        assert isinstance(td, ArrayDict)
        assert td["observation"].shape == (5, 3)
        assert td["nested", "x"].shape == (5, 2)
        assert spec.is_in(td)
        assert spec.is_in(spec.zero((2,)))

    def test_batch_shape_propagates(self):
        spec = Composite({"a": Unbounded(shape=(2,))}, shape=(4,))
        td = spec.rand(KEY)
        assert td["a"].shape == (4, 2)
        assert spec.expand(3, 4).shape == (3, 4)

    def test_missing_key_not_in(self):
        spec = self.make()
        td = spec.rand(KEY).exclude("action")
        assert not spec.is_in(td)

    def test_set_delete_update(self):
        spec = self.make()
        spec2 = spec.set(("nested", "y"), Binary(shape=(1,)))
        assert ("nested", "y") in spec2
        spec3 = spec2.delete("action")
        assert "action" not in spec3
        spec4 = spec.update(Composite(action=Categorical(n=9)))
        assert spec4["action"].n == 9
        assert "observation" in spec4

    def test_project(self):
        spec = self.make()
        bad = ArrayDict(
            observation=jnp.full((3,), 5.0),
            action=jnp.array(99, jnp.int32),
            nested=ArrayDict(x=jnp.zeros(2)),
        )
        fixed = spec.project(bad)
        assert spec.is_in(fixed)

    def test_to_sds_tree(self):
        spec = self.make()
        sds = spec.to_sds((7,))
        assert sds["observation"].shape == (7, 3)

    def test_keys_nested(self):
        spec = self.make()
        assert ("nested", "x") in spec.keys(nested=True, leaves_only=True)

    def test_eq(self):
        assert self.make() == self.make()
        assert self.make() != self.make().delete("action")


class TestStackAndInfer:
    def test_stack_specs_leaf(self):
        s = stack_specs([Unbounded(shape=(3,))] * 4)
        assert s.shape == (4, 3)

    def test_stack_specs_composite(self):
        c = Composite(a=Unbounded(shape=(2,)))
        s = stack_specs([c, c])
        # Batch shape grows; child feature shapes stay put.
        assert s.shape == (2,)
        assert s["a"].shape == (2,)
        assert s.rand(KEY)["a"].shape == (2, 2)

    def test_stack_heterogeneous_returns_masked_stack(self):
        # round 4: ragged members now produce the mask-backed Stacked
        # (full behavior in tests/test_hetero_specs.py)
        from rl_tpu.data import Stacked

        s = stack_specs([Unbounded(shape=(2,)), Unbounded(shape=(3,))])
        assert isinstance(s, Stacked) and s.shape == (2, 3)
        # mixed TYPES still raise
        with pytest.raises(ValueError):
            stack_specs([Unbounded(shape=(2,)), Bounded(shape=(2,), low=0, high=1)])

    def test_make_composite_from_arraydict(self):
        td = ArrayDict(obs=jnp.zeros((4, 3)), nested=ArrayDict(r=jnp.zeros(4)))
        spec = make_composite_from_arraydict(td)
        assert spec["obs"].shape == (4, 3)
        assert spec.is_in(td)


class TestRegressions:
    """Pinned fixes from review: shape double-counting, sharding, projection."""

    def test_nested_dict_batch_shape_once(self):
        spec = Composite({"a": {"x": Unbounded(shape=(3,))}}, shape=(4,))
        assert spec.rand(KEY)["a", "x"].shape == (4, 3)
        assert spec.zero()["a", "x"].shape == (4, 3)

    def test_to_sds_includes_own_batch_shape(self):
        spec = Composite({"a": Unbounded(shape=(3,))}, shape=(4,))
        assert spec.to_sds()["a"].shape == (4, 3)
        assert spec.to_sds((2,))["a"].shape == (2, 4, 3)

    def test_composite_with_sharding(self):
        from jax.sharding import PartitionSpec

        spec = Composite(a=Unbounded(shape=(3,)))
        sh = spec.with_sharding(PartitionSpec("data"))
        assert sh["a"].sharding == PartitionSpec("data")

    def test_categorical_unknown_n_project_passthrough(self):
        vals = jnp.array([0, 1, 2], jnp.int32)
        np.testing.assert_array_equal(Categorical().project(vals), vals)

    def test_seed_generator(self):
        from rl_tpu.utils import seed_generator

        s1 = seed_generator(42)
        assert s1 == seed_generator(42) != seed_generator(s1)

    def test_arraydict_delete_through_leaf_keyerror(self):
        td = ArrayDict(a=jnp.zeros(3))
        with pytest.raises(KeyError):
            td.delete(("a", "sub"))
        # exclude() swallows the KeyError and must not free the buffer
        out = td.exclude(("a", "sub"))
        assert float(out["a"].sum()) == 0.0

    def test_arraydict_eq_structure_mismatch(self):
        assert (ArrayDict(x=jnp.zeros(3)) == ArrayDict(y=jnp.zeros(3))) is False
