"""Speculative decoding (ISSUE 16): draft sources + the exactness gate.

Strategy: speculation is *self*-speculation — the verify program samples
every position with the same per-(rid, token-index) key sequential
decode would use, and accepts a draft token only when it EQUALS the
sample.  So the contract under test is not "approximately the same
distribution" but bitwise identity: (1) a seeded speculative engine
must emit exactly the tokens a vanilla engine emits from the same seed,
greedy AND temperature, across eos-mid-draft, draft-longer-than-budget,
and prefix-cache-replay shapes; (2) steady state stays compile-free —
every verify width rides the warmed decode ladder (``CompileDelta ==
0``, plus the rlint ``check_spec_programs`` name gate); (3) a fleet
with speculation on every member keeps the exactly-once accounting
(``lost == 0``) through an injected mid-decode crash."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.compile import CompileDelta, ShapeBuckets
from rl_tpu.compile.auditset import check_spec_programs
from rl_tpu.models import (
    ContinuousBatchingEngine,
    DraftSource,
    FinishedRequest,
    NGramDraft,
    PrefixTreeDraft,
    ServingFleet,
    TransformerConfig,
    TransformerLM,
)
from rl_tpu.models.speculative import sample_tokens, slot_keys, spec_keys
from rl_tpu.obs import MetricsRegistry
from rl_tpu.resilience import Fault, FaultInjector, injection

# rlint runtime sanitizer: every lock created inside these tests is
# witnessed; any observed lock-order inversion fails the test at teardown
pytestmark = pytest.mark.usefixtures("lock_witness")

KEY = jax.random.key(0)


def small_model():
    cfg = TransformerConfig(
        vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq_len=128, dtype=jnp.float32,
    )
    m = TransformerLM(cfg)
    params = m.init(KEY, jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


_MODEL = small_model()  # one compile cache for the whole module


def _engine(**kw):
    m, params = _MODEL
    kw.setdefault("n_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("n_blocks", 65)
    kw.setdefault("prompt_buckets", (16,))
    kw.setdefault("greedy", True)
    kw.setdefault("seed", 7)
    return ContinuousBatchingEngine(m, params, **kw)


def _complete(eng, prompts, max_new):
    rids = [eng.submit(p, max_new) for p in prompts]
    out = eng.run()
    return [out[r] for r in rids]


def _assert_same(got, want, lp_atol=1e-5):
    """Tokens bit-identical; log-probs only float-close (the verify
    forward is one K-wide GEMM, sequential decode is K 1-wide GEMMs —
    same math, different reduction shapes)."""
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert np.array_equal(g.tokens, w.tokens), (g.tokens, w.tokens)
        assert g.finished_reason == w.finished_reason
        np.testing.assert_allclose(g.log_probs, w.log_probs, rtol=0,
                                   atol=lp_atol)


class OracleDraft:
    """DraftSource that replays reference continuations: proposes the
    rest of whichever reference sequence the slot context is a prefix
    of.  A perfect draft source — it forces long accepted chains, so the
    exactness matrix exercises the verify's accept path hard instead of
    depending on whatever an n-gram heuristic happens to guess."""

    def __init__(self, seqs):
        self.seqs = [list(map(int, s)) for s in seqs]
        self.hits = 0
        self.misses = 0
        self.proposed_tokens = 0

    def propose(self, context, k):
        c = list(map(int, context))
        for s in self.seqs:
            if len(s) > len(c) and s[: len(c)] == c:
                out = s[len(c): len(c) + k]
                self.hits += 1
                self.proposed_tokens += len(out)
                return out
        self.misses += 1
        return []

    def stats(self):
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hits / total if total else 0.0,
            "proposed_tokens": self.proposed_tokens,
        }


# ---------------------------------------------------------------------------
# the shared sampling helper + slot-stream key derivation


class TestSharedSampler:
    def test_engine_sample_delegates_to_shared_helper(self):
        eng = _engine(greedy=False, temperature=0.7)
        logits = jax.random.normal(jax.random.key(3), (4, 97))
        key = jax.random.key(11)
        tok_e, lp_e = eng._sample(logits, key)
        tok_h, lp_h = sample_tokens(logits, key, temperature=0.7, greedy=False)
        assert np.array_equal(np.asarray(tok_e), np.asarray(tok_h))
        assert np.array_equal(np.asarray(lp_e), np.asarray(lp_h))

    def test_greedy_ignores_key(self):
        logits = jax.random.normal(jax.random.key(4), (3, 97))
        a = sample_tokens(logits, jax.random.key(0), temperature=1.0, greedy=True)
        b = sample_tokens(logits, jax.random.key(9), temperature=1.0, greedy=True)
        assert np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
        assert np.array_equal(np.asarray(a[0]),
                              np.asarray(jnp.argmax(logits, axis=-1)))

    def test_per_row_keys_match_row_by_row_draws(self):
        logits = jax.random.normal(jax.random.key(5), (4, 97))
        keys = slot_keys(jax.random.key(1),
                         jnp.arange(4, dtype=jnp.int32),
                         jnp.arange(4, dtype=jnp.int32) * 3)
        tok, lp = sample_tokens(logits, keys, temperature=0.7, greedy=False)
        for i in range(4):
            ti, li = sample_tokens(logits[i: i + 1], keys[i],
                                   temperature=0.7, greedy=False)
            assert int(tok[i]) == int(ti[0])
            assert float(lp[i]) == float(li[0])

    def test_spec_keys_are_the_sequential_decode_keys(self):
        # verify position j of slot s must key token index ntok[s] + j of
        # rid[s] — EXACTLY what the decode scan derives step by step
        base = jax.random.key(2)
        rids = jnp.asarray([5, 9], jnp.int32)
        ntoks = jnp.asarray([0, 4], jnp.int32)
        grid = spec_keys(base, rids, ntoks, 3)
        for s in range(2):
            for j in range(3):
                want = slot_keys(base, rids[s: s + 1], ntoks[s: s + 1] + j)
                assert np.array_equal(
                    np.asarray(jax.random.key_data(grid[s, j])),
                    np.asarray(jax.random.key_data(want))[0],
                )


# ---------------------------------------------------------------------------
# draft sources


class TestDraftSources:
    def test_protocol_runtime_checkable(self):
        assert isinstance(NGramDraft(), DraftSource)
        assert isinstance(OracleDraft([]), DraftSource)

    def test_ngram_proposes_followers_of_trailing_ngram(self):
        d = NGramDraft(n=2)
        #         match here v v        tail v v
        ctx = [1, 2, 3, 4, 5, 8, 9, 6, 7, 0, 8, 9]
        assert d.propose(ctx, 3) == [6, 7, 0]
        assert d.propose(ctx, 1) == [6]
        assert d.stats()["hits"] == 2 and d.stats()["proposed_tokens"] == 4

    def test_ngram_misses_without_repetition(self):
        d = NGramDraft(n=3)
        assert d.propose([1, 2, 3, 4, 5, 6], 4) == []
        assert d.propose([1, 2], 4) == []  # shorter than the n-gram
        assert d.propose([1, 2, 3, 4], 0) == []
        assert d.stats()["hits"] == 0 and d.stats()["hit_rate"] == 0.0

    def test_ngram_rejects_bad_n(self):
        with pytest.raises(ValueError):
            NGramDraft(n=0)

    def test_prefix_tree_draft_replays_donated_continuation(self):
        eng = _engine(prefix_cache=True)
        prompt = np.arange(30, 42) % 97
        rid = eng.submit(prompt, 10)
        ref = eng.run()[rid]
        src = PrefixTreeDraft(eng._kvmem)
        ctx = list(map(int, prompt)) + list(map(int, ref.tokens[:2]))
        out = src.propose(ctx, 5)
        want = list(map(int, ref.tokens[2:]))
        assert out and out == want[: len(out)]
        s = src.stats()
        assert s["hits"] >= 1 and s["proposed_tokens"] == len(out)
        assert 0.0 < s["hit_rate"] <= 1.0


# ---------------------------------------------------------------------------
# the exactness matrix: speculative output == vanilla output, bitwise


class TestExactness:
    PROMPTS = [np.arange(3, 15) % 97, np.arange(60, 72) % 97]

    def test_greedy_oracle_spec_matches_legacy(self):
        ref = _complete(_engine(), self.PROMPTS, 12)
        oracle = OracleDraft(
            [list(p) + list(r.tokens) for p, r in zip(self.PROMPTS, ref)]
        )
        spec = _engine(speculative=True, draft_source=oracle, spec_lookahead=7)
        out = _complete(spec, self.PROMPTS, 12)
        _assert_same(out, ref)
        assert spec.spec_dispatches >= 1
        # a perfect draft source accepts whole chains: > 1 token/dispatch
        assert spec.spec_accepted_tokens > spec.spec_dispatches

    def test_greedy_ngram_spec_matches_legacy(self):
        # repetitive prompts so prompt-lookup actually drafts; exactness
        # must hold whether the n-gram guesses right or wrong
        prompts = [np.tile([5, 6, 7, 8], 4), np.tile([40, 41], 8)]
        ref = _complete(_engine(), prompts, 12)
        spec = _engine(speculative=True, draft_source="ngram")
        out = _complete(spec, prompts, 12)
        _assert_same(out, ref)

    def test_temperature_spec_matches_vanilla_slot_stream(self):
        van = _engine(greedy=False, temperature=0.7, slot_rng=True, seed=11)
        ref = _complete(van, self.PROMPTS, 12)
        oracle = OracleDraft(
            [list(p) + list(r.tokens) for p, r in zip(self.PROMPTS, ref)]
        )
        spec = _engine(greedy=False, temperature=0.7, speculative=True,
                       draft_source=oracle, spec_lookahead=7, seed=11)
        out = _complete(spec, self.PROMPTS, 12)
        _assert_same(out, ref)
        assert spec.spec_dispatches >= 1
        assert spec.spec_accepted_tokens > spec.spec_dispatches

    def test_eos_mid_draft_stops_identically(self):
        prompt = np.arange(11, 23) % 97
        ref = _complete(_engine(), [prompt], 16)[0]
        eos = int(ref.tokens[3])
        stop = int(np.flatnonzero(ref.tokens == eos)[0])
        oracle = OracleDraft([list(prompt) + list(ref.tokens)])  # drafts PAST eos
        van = _complete(_engine(eos_id=eos), [prompt], 16)[0]
        out = _complete(
            _engine(eos_id=eos, speculative=True, draft_source=oracle,
                    spec_lookahead=7),
            [prompt], 16,
        )[0]
        _assert_same([out], [van])
        assert out.finished_reason == "eos"
        assert np.array_equal(out.tokens, ref.tokens[: stop + 1])

    def test_draft_longer_than_remaining_budget(self):
        prompt = np.arange(17, 29) % 97
        ref = _complete(_engine(), [prompt], 12)[0]
        oracle = OracleDraft([list(prompt) + list(ref.tokens)])
        spec = _engine(speculative=True, draft_source=oracle, spec_lookahead=7)
        out = _complete(spec, [prompt], 3)[0]  # budget 3 << lookahead 7
        assert out.finished_reason == "length"
        assert np.array_equal(out.tokens, ref.tokens[:3])
        want = _complete(_engine(), [prompt], 3)[0]
        _assert_same([out], [want])

    def test_prefix_cache_replay_identical_with_tree_drafts(self):
        ref = _complete(_engine(), self.PROMPTS, 12)
        eng = _engine(prefix_cache=True, speculative=True, spec_lookahead=7)
        out1 = _complete(eng, self.PROMPTS, 12)  # cold: donates the tree
        out2 = _complete(eng, self.PROMPTS, 12)  # replay: real tree drafts
        _assert_same(out1, ref)
        _assert_same(out2, ref)
        assert eng.spec_dispatches >= 1
        snap = eng.metrics_snapshot()
        assert snap["spec_accepted_per_dispatch"] > 1.0
        assert snap["spec_draft_hits"] >= 1
        assert 0.0 < snap["spec_draft_hit_rate"] <= 1.0
        # one histogram entry per VALID SLOT per verify (a dispatch
        # carrying two live requests records two accepted-run lengths)
        assert sum(snap["spec_accept_counts"].values()) >= eng.spec_dispatches
        eng._kvmem.audit()

    def test_speculative_off_path_untouched(self):
        eng = _engine()
        assert not eng.speculative and not eng.slot_rng
        assert eng._sadmit_update is None and eng._draft_source is None
        snap = _complete(eng, [self.PROMPTS[0]], 4) and eng.metrics_snapshot()
        assert "spec_dispatches" not in snap


# ---------------------------------------------------------------------------
# compile-free steady state


class TestCompileFree:
    def test_spec_steady_state_compile_delta_zero(self):
        eng = _engine(
            prefix_cache=True, speculative=True, spec_lookahead=7,
            prompt_buckets=None,
            buckets=ShapeBuckets(prompt=(32, 64), suffix=(8, 16)),
        )
        eng.aot_warmup()
        rng = np.random.default_rng(5)
        sysp = rng.integers(1, 97, size=21)
        # ONE fixed request list replayed verbatim (test_kvmem's
        # steady-state idiom): replays keep the admission grouping stable
        # AND give the radix tree exact donors to draft from
        reqs = [np.concatenate([sysp, rng.integers(1, 97, size=4)])
                for _ in range(4)]

        def traffic():
            for r in reqs:
                eng.submit(r, 6)
            eng.run()

        # warm-up rounds absorb one-time host-glue compiles (see
        # test_kvmem.test_compile_free_steady_state for why TWO clean
        # rounds are demanded before measuring)
        clean = 0
        for _ in range(12):
            with CompileDelta() as glue:
                traffic()
            clean = clean + 1 if (not glue.supported or glue.delta == 0) else 0
            if clean >= 2:
                break
        before = eng.spec_dispatches
        with CompileDelta() as steady:
            traffic()
        assert not steady.supported or steady.delta == 0, steady.explain()
        # the measured round really speculated — the delta above gates
        # the verify ladder, not an accidentally-vanilla round
        assert eng.spec_dispatches > before
        eng._kvmem.audit()


# ---------------------------------------------------------------------------
# fleet chaos with speculation on every member


def _wait_until(pred, timeout=30.0, msg="condition"):
    import time

    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.005)


class TestFleetChaosSpeculative:
    def test_crash_mid_decode_spec_exactly_once(self):
        m, params = _MODEL
        engines = [
            ContinuousBatchingEngine(
                m, params, n_slots=2, block_size=8, n_blocks=65,
                prompt_buckets=(16,), greedy=True, seed=i,
                prefix_cache=True, speculative=True, spec_lookahead=5,
            )
            for i in range(2)
        ]
        for e in engines:  # compile outside the fleet (liveness probes)
            e.submit(np.arange(8), 4)
            e.run()
        fleet = ServingFleet(engines, registry=MetricsRegistry(),
                             probe_interval_s=0.01).start()
        try:
            rng = np.random.default_rng(0)
            base = rng.integers(0, 97, 8)
            # one shared prompt: replays draft from the radix tree, so the
            # crash lands while verify dispatches are genuinely in play
            frids = [fleet.submit(base.copy(), 24) for _ in range(6)]
            _wait_until(lambda: engines[0].pending() > 0, msg="engine 0 busy")
            inj = FaultInjector(
                {"fleet.engine_crash.0": Fault("crash", at=(1,))},
                registry=MetricsRegistry(),
            )
            with injection(inj):
                got = fleet.wait(frids, timeout=120)
            assert sorted(got) == sorted(frids)
            assert all(isinstance(r, FinishedRequest) for r in got.values())
            acc = fleet.accounting()
            assert acc["completed"] == len(frids)
            assert acc["lost"] == 0
            assert acc["redispatched"] >= 1  # engine 0 WAS mid-decode
            # the run actually speculated somewhere (shared prompt replays)
            assert sum(e.spec_dispatches for e in engines) >= 1
            # every copy of the shared prompt got the same greedy answer
            toks = [got[f].tokens for f in frids]
            assert all(np.array_equal(t, toks[0]) for t in toks[1:])
        finally:
            fleet.shutdown()


# ---------------------------------------------------------------------------
# rlint gate: spec programs may never leave the warmed ladder


class _FakeRegistry:
    def __init__(self, names):
        self._names = list(names)

    def names(self):
        return list(self._names)


class TestSpecProgramGate:
    def test_ladder_names_pass(self):
        check_spec_programs(_FakeRegistry([
            "serving.decode.k4",
            "serving.verify.k8",
            "serving.sdecode.k1",
            "serving.sprefill.a2.b16",
            "serving.spprefill.a2.s8",
            "serving.sadmit_update",
            "serving.admit_update",
            "anakin.step",
        ]))

    def test_off_ladder_verify_rejected(self):
        with pytest.raises(RuntimeError, match="off the decode ladder"):
            check_spec_programs(_FakeRegistry(["serving.verify.k5"]))

    def test_off_ladder_sdecode_rejected(self):
        with pytest.raises(RuntimeError, match="off the decode ladder"):
            check_spec_programs(_FakeRegistry(["serving.sdecode.k3"]))

    def test_unknown_spec_family_rejected(self):
        with pytest.raises(RuntimeError, match="unknown speculative-path"):
            check_spec_programs(_FakeRegistry(["serving.spec_extra.k4"]))

    def test_live_registry_clean(self):
        from rl_tpu.compile.registry import get_program_registry

        check_spec_programs(get_program_registry())
