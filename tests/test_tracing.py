"""Causal request tracing, SLO burn-rate engine, and crash flight
recorder (ISSUE 12).

The acceptance spine: a fleet chaos run (injected ``fleet.engine_crash``
mid-decode) must render as ONE parent-linked trace tree spanning >= 3
threads and >= 1 TCP hop, with the failover re-dispatch span parented to
the original request span — verified here by walking the Perfetto
export. Around it: TraceContext propagation across thread and wire
boundaries, the timestamp-interleaved export fix, the SLO engine's
attainment/burn-rate math and gauges, the flight recorder's postmortem
bundle on an injected Supervisor budget exhaustion, and the <5%
tracing-overhead bound on a fused device cycle."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    Objective,
    SLOEngine,
    StreamingHistogram,
    TraceContext,
    TraceRecorder,
    carry_context,
    ctx_args,
    current_context,
    new_trace,
    set_registry,
    set_tracer,
    use_context,
)
from rl_tpu.obs.flight import set_flight_recorder

# imported at module scope (not inside tests): the lock_witness fixture
# wraps threading.Lock while armed, and stdlib modules imported mid-test
# (concurrent.futures.thread via the collectors) break under the wrap
from rl_tpu.collectors import AsyncHostCollector, ThreadedEnvPool
from rl_tpu.comm import TCPCommandClient, TCPCommandServer
from rl_tpu.comm.liveness import Watchdog
from rl_tpu.data.specs import Bounded, Composite, Unbounded
from rl_tpu.models import (
    ContinuousBatchingEngine,
    FinishedRequest,
    ServingFleet,
    TransformerConfig,
    TransformerLM,
)
from rl_tpu.resilience import Fault, FaultInjector, Supervisor, injection
from rl_tpu.resilience.faults import fault_point

# rlint runtime sanitizer: every lock created inside these tests is
# witnessed; any observed lock-order inversion fails the test at teardown
pytestmark = pytest.mark.usefixtures("lock_witness")


@pytest.fixture
def fresh_obs():
    """Fresh process-default registry + tracer (restored after); the
    propagation hooks all record into the process default, so tests must
    never see each other's events."""
    reg, tracer = MetricsRegistry(), TraceRecorder()
    prev_reg, prev_tracer = set_registry(reg), set_tracer(tracer)
    yield reg, tracer
    set_registry(prev_reg)
    set_tracer(prev_tracer)


def _events(tracer, name=None):
    evs = tracer.export()["traceEvents"]
    return [e for e in evs if name is None or e.get("name") == name]


# -- TraceContext ---------------------------------------------------------


class TestTraceContext:
    def test_child_links_under_parent_same_trace(self):
        root = new_trace()
        assert root.parent_id is None
        kid = root.child()
        assert kid.trace_id == root.trace_id
        assert kid.parent_id == root.span_id
        assert kid.span_id != root.span_id

    def test_wire_round_trip(self):
        kid = new_trace().child()
        assert TraceContext.from_wire(kid.to_wire()) == kid
        root = new_trace()
        assert "parent_id" not in root.to_wire()
        assert TraceContext.from_wire(root.to_wire()) == root

    def test_from_wire_tolerates_garbage(self):
        # old peers / hand-written clients: trace metadata must never
        # fail the control plane
        for junk in (None, "x", 7, [], {}, {"trace_id": 1, "span_id": "s"},
                     {"trace_id": "t"}):
            assert TraceContext.from_wire(junk) is None

    def test_ctx_args_active_and_explicit(self):
        assert ctx_args() == {}
        kid = new_trace().child()
        with use_context(kid):
            a = ctx_args()
            assert a == {"trace_id": kid.trace_id, "span_id": kid.span_id,
                         "parent_id": kid.parent_id}
        assert ctx_args() == {}
        assert ctx_args(kid)["span_id"] == kid.span_id


class TestThreadPropagation:
    def test_plain_thread_does_not_carry(self):
        got = {"ctx": "unset"}
        with use_context(new_trace()):
            t = threading.Thread(
                target=lambda: got.update(ctx=current_context()))
            t.start()
            t.join()
        assert got["ctx"] is None  # why carry_context exists

    def test_carry_context_crosses_thread(self):
        got = {}
        root = new_trace()
        with use_context(root):
            t = threading.Thread(target=carry_context(
                lambda: got.update(ctx_args())))
        t.start()  # started OUTSIDE the block: capture happened at wrap
        t.join()
        assert got["trace_id"] == root.trace_id
        assert got["span_id"] == root.span_id

    def test_supervisor_child_inherits_spawn_context(self):
        sup = Supervisor(name="t", registry=MetricsRegistry())
        got, done = {}, threading.Event()

        def child():
            got.update(ctx_args())
            done.set()

        root = new_trace()
        try:
            with use_context(root):
                sup.spawn("probe", child, escalate=False)
            assert done.wait(10.0)
        finally:
            sup.stop()
        assert got["trace_id"] == root.trace_id


class TestCtxSpan:
    def test_derives_activates_and_stamps(self):
        tracer = TraceRecorder()
        root = new_trace()
        with use_context(root):
            with tracer.ctx_span("op", {"k": 1}) as ctx:
                assert current_context() is ctx
                assert ctx.parent_id == root.span_id
                assert ctx.trace_id == root.trace_id
            assert current_context() is root  # restored
        (ev,) = _events(tracer, "op")
        assert ev["ph"] == "X" and ev["args"]["k"] == 1
        assert ev["args"]["span_id"] == ctx.span_id
        assert ev["args"]["parent_id"] == root.span_id

    def test_roots_new_trace_without_active_context(self):
        tracer = TraceRecorder()
        with tracer.ctx_span("root_op") as ctx:
            assert ctx.parent_id is None
        (ev,) = _events(tracer, "root_op")
        assert "parent_id" not in ev["args"]

    def test_disabled_recorder_no_derivation_no_event(self):
        tracer = TraceRecorder(enabled=False)
        root = new_trace()
        with use_context(root):
            with tracer.ctx_span("op") as ctx:
                assert ctx is root  # zero propagation overhead when off
        assert _events(tracer, "op") == []


# -- export interleave (satellite c) --------------------------------------


class TestExportInterleave:
    def test_cross_thread_events_sorted_by_timestamp(self):
        tracer = TraceRecorder()

        def rec(name):
            t = threading.Thread(target=lambda: tracer.instant(name))
            t.start()
            t.join()

        tracer.instant("e0")  # main ring
        rec("e1")             # ring 2
        tracer.instant("e2")  # main ring again
        rec("e3")             # ring 3 (fresh thread, fresh ring)
        evs = tracer.export()["traceEvents"]
        instants = [e for e in evs if e["ph"] == "i"]
        # per-ring grouping would give e0,e2,e1,e3 — the regression fixed
        assert [e["name"] for e in instants] == ["e0", "e1", "e2", "e3"]
        assert instants[0]["tid"] != instants[1]["tid"]
        # thread-name metadata carries no ts and must lead the stream
        n_meta = sum(1 for e in evs if e["ph"] == "M")
        assert n_meta == 3
        assert all(e["ph"] == "M" for e in evs[:n_meta])

    def test_span_sorts_by_start_not_end(self):
        tracer = TraceRecorder()
        with tracer.span("outer"):
            tracer.instant("inner")
        names = [e["name"] for e in tracer.export()["traceEvents"]
                 if e["ph"] in ("X", "i")]
        assert names == ["outer", "inner"]


# -- TCP propagation ------------------------------------------------------


class TestTCPPropagation:
    def test_wire_context_links_handler_under_caller(self, fresh_obs):
        _, tracer = fresh_obs
        seen = {}
        srv = TCPCommandServer().start()
        try:
            def handler(payload):
                seen.update(ctx_args())
                return payload

            srv.register_handler("work", handler)
            host, port = srv.address
            cli = TCPCommandClient(host, port)
            root = new_trace()
            with use_context(root):
                assert cli.call("work", 42) == 42
        finally:
            srv.shutdown()
        (call,) = _events(tracer, "comm/call:work")
        (handle,) = _events(tracer, "comm/handle:work")
        # one TCP hop: the handler span (server thread) hangs under the
        # call span (client thread), same trace as the caller's root
        assert call["args"]["trace_id"] == root.trace_id
        assert call["args"]["parent_id"] == root.span_id
        assert handle["args"]["trace_id"] == root.trace_id
        assert handle["args"]["parent_id"] == call["args"]["span_id"]
        assert handle["tid"] != call["tid"]
        # the handler body ran under the handle span's context
        assert seen["parent_id"] == call["args"]["span_id"]

    def test_untraced_call_sends_no_trace_key(self, fresh_obs):
        from rl_tpu.comm import TCPCommandClient, TCPCommandServer

        _, tracer = fresh_obs
        seen = {}
        srv = TCPCommandServer().start()
        try:
            srv.register_handler("work", lambda p: seen.update(ctx_args()) or p)
            host, port = srv.address
            assert current_context() is None
            assert TCPCommandClient(*srv.address).call("work", 1) == 1
        finally:
            srv.shutdown()
        assert seen == {}  # wire-compatible both directions
        assert _events(tracer, "comm/call:work") == []


# -- fault stamping (satellite b) -----------------------------------------


class TestFaultTraceLink:
    def test_fired_fault_carries_active_context(self, fresh_obs):
        _, tracer = fresh_obs
        inj = FaultInjector(
            {"grpo.rollout": Fault("delay", at=(2,), seconds=0.0)},
            registry=MetricsRegistry(),
        )
        root = new_trace()
        with injection(inj):
            fault_point("grpo.rollout")  # n=1: no fire, outside any ctx
            with use_context(root):
                fault_point("grpo.rollout")  # n=2: fires inside the ctx
        # the `fired` tuple shape is load-bearing for older chaos tests
        assert inj.fired == [("grpo.rollout", "delay", 2)]
        assert inj.fired_trace == [
            {"trace_id": root.trace_id, "span_id": root.span_id}
        ]
        (ev,) = _events(tracer, "fault_injected")
        assert ev["args"]["trace_id"] == root.trace_id
        assert ev["args"]["site"] == "grpo.rollout"

    def test_unfired_and_untraced_visits(self):
        inj = FaultInjector(
            {"grpo.rollout": Fault("delay", at=(1,), seconds=0.0)},
            registry=MetricsRegistry(), tracer=TraceRecorder(),
        )
        with injection(inj):
            fault_point("grpo.rollout")  # fires with no context active
        assert inj.fired_trace == [None]


# -- SLO engine -----------------------------------------------------------


class TestStreamingHistogram:
    def test_observe_quantile_interpolates(self):
        h = StreamingHistogram(edges=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.5, 3.0):
            h.observe(v)
        assert h.count == 4 and h.sum == pytest.approx(6.5)
        # rank q*n lands mid-bucket; linear within the bucket
        assert 0.0 < h.quantile(0.25) <= 1.0
        assert 1.0 < h.quantile(0.5) <= 2.0
        assert 2.0 < h.quantile(1.0) <= 4.0

    def test_overflow_clamps_to_last_edge(self):
        h = StreamingHistogram(edges=(1.0, 2.0))
        h.observe(100.0)
        assert h.quantile(0.99) == 2.0

    def test_empty_is_none_and_bad_q_raises(self):
        h = StreamingHistogram()
        assert h.quantile(0.5) is None
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_merge_rolls_up_same_edges_only(self):
        a = StreamingHistogram(edges=(1.0, 2.0))
        b = StreamingHistogram(edges=(1.0, 2.0))
        a.observe(0.5)
        b.observe(1.5)
        a.merge(b)
        assert a.count == 2 and a.sum == pytest.approx(2.0)
        assert a.counts == [1, 1, 0]
        with pytest.raises(ValueError):
            a.merge(StreamingHistogram(edges=(1.0, 3.0)))

    def test_bad_edges_raise(self):
        for edges in ((), (1.0, 1.0), (2.0, 1.0)):
            with pytest.raises(ValueError):
                StreamingHistogram(edges=edges)


class TestObjective:
    def test_attainment_and_burn_rate_windows(self):
        t = [1000.0]
        o = Objective("ttft", threshold=1.0, target=0.9, ring_s=3600,
                      clock=lambda: t[0])
        for v in (0.5, 0.5, 2.0, 0.5):
            o.record(v)
        assert o.attainment() == pytest.approx(0.75)
        assert o.attainment(60.0) == pytest.approx(0.75)
        assert o.burn_rate(60.0) == pytest.approx(0.25 / 0.1)
        t[0] += 120.0  # events age out of the 60s window
        assert o.attainment(60.0) is None
        assert o.burn_rate(60.0) == 0.0  # idle service burns nothing
        assert o.attainment() == pytest.approx(0.75)  # all-time unchanged

    def test_ring_lapping_discards_stale_slots(self):
        t = [50.0]
        o = Objective("x", threshold=1.0, ring_s=10, clock=lambda: t[0])
        o.record(0.5)
        t[0] += 10.0  # exactly one lap: same slot, different second
        o.record(0.5)
        g, tot = o._window_counts(10.0)
        assert (g, tot) == (1, 1)  # the lapped write invalidated the old slot

    def test_event_objective_and_type_guard(self):
        o = Objective("avail", threshold=None, target=0.5)
        o.record_event(True)
        o.record_event(False)
        assert o.attainment() == pytest.approx(0.5)
        assert o.burn_rate(60.0) == pytest.approx(1.0)  # exactly sustainable
        with pytest.raises(ValueError, match="event-based"):
            o.record(1.0)

    def test_good_is_strictly_threshold_le(self):
        o = Objective("x", threshold=1.0)
        assert o.record(1.0) is True
        assert o.record(1.0001) is False


class TestSLOEngine:
    def test_gauges_published_on_first_scrape(self):
        reg = MetricsRegistry()
        eng = SLOEngine(registry=reg)
        o = eng.objective("ttft", threshold=1.0, target=0.9)
        o.record(0.5)
        o.record(2.0)
        text = reg.render()
        # families must exist on the FIRST render (created at init, not
        # inside the collector: render snapshots families pre-collector)
        assert 'rl_tpu_slo_attainment{slo="ttft",window="all"} 0.5' in text
        assert 'rl_tpu_slo_attainment{slo="ttft",window="60s"} 0.5' in text
        assert 'rl_tpu_slo_burn_rate{slo="ttft",window="60s"} 5' in text
        assert 'rl_tpu_slo_value_seconds{slo="ttft",quantile="0.5"}' in text
        assert 'rl_tpu_slo_value_seconds{slo="ttft",quantile="0.99"}' in text

    def test_objective_idempotent_or_loud(self):
        eng = SLOEngine()
        a = eng.objective("x", threshold=1.0)
        assert eng.objective("x", threshold=1.0) is a
        with pytest.raises(ValueError, match="already defined"):
            eng.objective("x", threshold=2.0)
        assert eng.names() == ["x"]
        assert eng.get("x") is a

    def test_snapshot_is_bench_artifact_shaped(self):
        eng = SLOEngine(windows=(60.0,))
        eng.objective("lat", threshold=1.0).record(0.5)
        snap = eng.snapshot()
        assert snap["lat"]["attainment"] == 1.0
        assert snap["lat"]["burn_rate_60s"] == 0.0
        assert "p50" in snap["lat"] and "p99" in snap["lat"]
        json.dumps(snap)  # must be artifact-serializable as-is


# -- flight recorder ------------------------------------------------------


class TestFlightRecorder:
    def test_bundle_contents(self, tmp_path, fresh_obs):
        reg, tracer = fresh_obs
        reg.counter("rl_tpu_test_total").inc(3)
        tracer.instant("before_death")
        rec = FlightRecorder(str(tmp_path), window_s=60.0)
        rec.add_source("acc", lambda: {"x": 1})
        rec.add_source("bad", lambda: 1 / 0)
        path = rec.dump("test_trigger", RuntimeError("boom"))
        assert path is not None and os.path.isdir(path)
        assert rec.dumps == [path]
        meta = json.load(open(os.path.join(path, "meta.json")))
        assert meta["trigger"] == "test_trigger"
        assert "boom" in meta["error"]
        assert meta["failed_artifacts"] == []
        trace = json.load(open(os.path.join(path, "trace.json")))
        assert any(e.get("name") == "before_death"
                   for e in trace["traceEvents"])
        metrics = json.load(open(os.path.join(path, "metrics.json")))
        assert "rl_tpu_test_total" in json.dumps(metrics)
        json.load(open(os.path.join(path, "programs.json")))
        assert json.load(open(os.path.join(path, "source-acc.json"))) == {"x": 1}
        # a raising source lands as its error, never kills the dump
        bad = json.load(open(os.path.join(path, "source-bad.json")))
        assert "ZeroDivisionError" in bad["error"]

    def test_window_cuts_old_events(self, tmp_path, fresh_obs):
        _, tracer = fresh_obs
        tracer.instant("old")
        time.sleep(0.3)  # "old" is >=0.3s stale at dump time
        tracer.instant("new")
        rec = FlightRecorder(str(tmp_path), window_s=0.15)
        path = rec.dump("t")
        names = [e.get("name") for e in
                 json.load(open(os.path.join(path, "trace.json")))["traceEvents"]]
        assert "new" in names and "old" not in names

    def test_rate_limit_and_cap(self, tmp_path):
        t = [0.0]
        rec = FlightRecorder(str(tmp_path), max_dumps=2, min_interval_s=1.0,
                             clock=lambda: t[0])
        assert rec.dump("a") is not None
        assert rec.dump("b") is None  # inside min_interval
        t[0] += 2.0
        assert rec.dump("c") is not None
        t[0] += 2.0
        assert rec.dump("d") is None  # max_dumps cap: bounded black box

    def test_dump_never_raises(self, tmp_path):
        blocker = tmp_path / "file"  # a FILE where the dump dir must go:
        blocker.write_text("x")      # makedirs fails even when run as root
        rec = FlightRecorder(str(blocker))
        assert rec.dump("t") is None

    def test_watchdog_death_triggers_dump(self, tmp_path, fresh_obs):
        rec = FlightRecorder(str(tmp_path))
        prev = set_flight_recorder(rec)
        try:
            wd = Watchdog(timeout=0.01)
            wd.register("actor-0")
            time.sleep(0.05)
            assert wd.check() == ["actor-0"]
        finally:
            set_flight_recorder(prev)
        assert len(rec.dumps) == 1
        meta = json.load(open(os.path.join(rec.dumps[0], "meta.json")))
        assert meta["trigger"] == "watchdog_death-actor-0"

    def test_budget_exhaustion_escalation_dumps_and_links_path(
            self, tmp_path, fresh_obs):
        """Acceptance: an injected Supervisor budget exhaustion produces a
        complete postmortem bundle whose path rides on the escalation
        error all the way out of ``get_batch``."""
        class _Env:
            observation_spec = Composite(observation=Unbounded((2,)))
            action_spec = Bounded(shape=(1,), low=-1.0, high=1.0)

            def reset(self, seed=None):
                return {"observation": np.zeros(2, np.float32)}

            def step(self, action):
                return (self.reset(), np.float32(0.0), False, False)

            def close(self):
                pass

        rec = FlightRecorder(str(tmp_path))
        prev = set_flight_recorder(rec)
        sup = Supervisor(name="t", max_restarts=1, backoff_base_s=0.005,
                         backoff_max_s=0.05, registry=MetricsRegistry())
        pool = ThreadedEnvPool([lambda: _Env() for _ in range(2)])
        coll = AsyncHostCollector(pool, None, frames_per_batch=16,
                                  supervisor=sup)
        inj = FaultInjector({"collector.actor_loop": Fault("crash", prob=1.0)},
                            registry=MetricsRegistry())
        try:
            with injection(inj):
                coll.start()
                with pytest.raises(RuntimeError,
                                   match="actor thread failed") as ei:
                    deadline = time.monotonic() + 60
                    while time.monotonic() < deadline:
                        coll.get_batch(timeout=0.2)
                    raise AssertionError("collector never exhausted budget")
        finally:
            coll.stop()
            sup.stop()
            pool.close()
            set_flight_recorder(prev)
        cause = ei.value.__cause__
        dump = getattr(cause, "flight_record", None)
        assert dump is not None and os.path.isdir(dump)
        assert rec.dumps == [dump]
        # the bundle is complete
        for artifact in ("meta.json", "trace.json", "metrics.json",
                         "programs.json"):
            assert os.path.isfile(os.path.join(dump, artifact))
        meta = json.load(open(os.path.join(dump, "meta.json")))
        assert meta["trigger"] == "supervisor_giveup-async-collector"
        assert "InjectedFault" in meta["error"]
        assert meta["failed_artifacts"] == []
        # the giveup instant in the trace marks the moment of death
        trace = json.load(open(os.path.join(dump, "trace.json")))
        assert any(e.get("name") == "supervisor_giveup"
                   for e in trace["traceEvents"])


# -- fleet chaos trace tree (the acceptance criterion) --------------------


def _small_model():
    cfg = TransformerConfig(
        vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq_len=128, dtype=jnp.float32,
    )
    m = TransformerLM(cfg)
    params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


def _wait_until(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.005)


class TestFleetTraceTree:
    def test_chaos_request_tree_spans_threads_and_tcp(self, fresh_obs,
                                                      tmp_path):
        """One interactive request's lifecycle — TCP submit, fleet admit,
        dispatch, injected mid-decode crash, failover re-dispatch,
        completion — renders as a single parent-linked tree."""
        reg, tracer = fresh_obs
        m, params = _small_model()
        engines = [
            ContinuousBatchingEngine(
                m, params, n_slots=2, block_size=8, n_blocks=65,
                prompt_buckets=(16,), greedy=True, seed=i,
            )
            for i in range(2)
        ]
        for e in engines:  # compile outside the fleet: no probe trips
            e.submit(np.arange(8), 4)
            e.run()
        fleet = ServingFleet(engines, registry=reg,
                             probe_interval_s=0.01).start()
        srv = TCPCommandServer().start()
        rng = np.random.default_rng(0)
        roots = {}
        try:
            srv.register_handler(
                "submit",
                lambda p: fleet.submit(np.asarray(p["prompt"]),
                                       p["max_new_tokens"]),
            )
            cli = TCPCommandClient(*srv.address)
            for _ in range(6):
                root = new_trace()
                with use_context(root):
                    frid = cli.call("submit", {
                        "prompt": rng.integers(0, 97, 8).tolist(),
                        "max_new_tokens": 24,
                    })
                roots[frid] = root
            _wait_until(lambda: engines[0].pending() > 0, msg="engine 0 busy")
            inj = FaultInjector(
                {"fleet.engine_crash.0": Fault("crash", at=(1,))},
                registry=MetricsRegistry(),
            )
            with injection(inj):
                got = fleet.wait(list(roots), timeout=90)
            assert sorted(got) == sorted(roots)
            assert all(isinstance(r, FinishedRequest) for r in got.values())
            acc = fleet.accounting()
            assert acc["lost"] == 0 and acc["redispatched"] >= 1
            scrape = reg.render()
        finally:
            srv.shutdown()
            fleet.shutdown()

        # ---- walk the Perfetto export ----
        out = tracer.export(str(tmp_path / "trace.json"))
        assert json.load(open(tmp_path / "trace.json")) == out
        evs = [e for e in out["traceEvents"]
               if e.get("args", {}).get("trace_id")]
        admits = {e["args"]["frid"]: e for e in evs
                  if e["name"] == "fleet_admit"}
        assert sorted(admits) == sorted(roots)
        fails = [e for e in evs if e["name"] == "fleet_failover_redispatch"]
        assert fails, "crash mid-decode must force >=1 failover re-dispatch"
        fail = fails[0]
        frid = fail["args"]["frid"]
        root, req = roots[frid], admits[frid]

        # (1) ONE tree: every leg shares the submitter's trace id, and the
        # failover re-dispatch is parented to the ORIGINAL request span
        assert req["args"]["trace_id"] == root.trace_id
        assert fail["args"]["trace_id"] == root.trace_id
        assert fail["args"]["parent_id"] == req["args"]["span_id"]

        # (2) parent-link chain from the request span back to the root
        # crosses the TCP hop: admit -> comm/handle -> comm/call -> root
        tree = [e for e in evs if e["args"]["trace_id"] == root.trace_id]
        by_span = {e["args"]["span_id"]: e for e in tree}
        chain, cur = [], req
        while cur["args"].get("parent_id") in by_span:
            cur = by_span[cur["args"]["parent_id"]]
            chain.append(cur["name"])
        assert chain == ["comm/handle:submit", "comm/call:submit"]
        assert cur["args"]["parent_id"] == root.span_id

        # (3) the tree spans >= 3 threads (client, TCP handler, fleet
        # dispatcher, member stepper...)
        assert len({e["tid"] for e in tree}) >= 3

        # dispatch + completion legs are present and correctly parented
        names = {e["name"] for e in tree}
        assert "fleet/dispatch" in names and "fleet_request_done" in names
        for e in tree:
            if e["name"] == "fleet/dispatch":
                assert e["args"]["parent_id"] == req["args"]["span_id"]

        # satellite b: the injected crash fired inside an admitted
        # request's context
        stamped = [c for c in inj.fired_trace if c]
        assert stamped
        assert stamped[0]["trace_id"] in {r.trace_id for r in roots.values()}

        # satellite a: real TTFT quantiles exported from the streaming
        # histogram (not the EMA), plus the fleet SLO burn-rate gauges
        assert 'rl_tpu_fleet_ttft_seconds{quantile="0.5"}' in scrape
        assert 'rl_tpu_fleet_ttft_seconds{quantile="0.99"}' in scrape
        assert 'rl_tpu_slo_attainment{slo="fleet_ttft",window="all"}' in scrape
        assert 'rl_tpu_slo_burn_rate{slo="fleet_availability"' in scrape
        snap = fleet.slo.snapshot()
        assert snap["fleet_availability"]["attainment"] == 1.0
        assert snap["fleet_latency"]["total"] == 6


# -- tracing overhead (satellite d) ---------------------------------------


class TestTracingOverhead:
    def test_armed_ctx_tracing_under_five_percent(self):
        """Tracing armed + context propagation on a fused device cycle
        stays inside the bench obs budget (overhead_frac < 0.05)."""
        tracer = TraceRecorder()
        prev = set_tracer(tracer)
        try:
            @jax.jit
            def fused(x):
                return jax.lax.fori_loop(
                    0, 200, lambda i, a: a @ a * 0.999 + 0.001, x)

            x = jnp.full((128, 128), 0.001, jnp.float32)
            jax.block_until_ready(fused(x))
            N = 20

            def run_plain():
                t0 = time.perf_counter()
                for _ in range(N):
                    jax.block_until_ready(fused(x))
                return time.perf_counter() - t0

            def run_traced():
                root = new_trace()
                t0 = time.perf_counter()
                with use_context(root):
                    for _ in range(N):
                        with tracer.ctx_span("cycle"):
                            jax.block_until_ready(fused(x))
                return time.perf_counter() - t0

            # interleaved best-of: the ratio divides near-equal numbers,
            # so one-sided wall jitter must not masquerade as overhead
            best_plain = best_traced = float("inf")
            for _ in range(5):
                best_plain = min(best_plain, run_plain())
                best_traced = min(best_traced, run_traced())
            frac = best_traced / best_plain - 1.0
            assert frac < 0.05, f"tracing overhead {frac:.3%} >= 5%"
            # and it actually traced: N spans per run, all context-linked
            spans = _events(tracer, "cycle")
            assert len(spans) == 5 * N
            assert all("trace_id" in e["args"] for e in spans)
        finally:
            set_tracer(prev)
