"""Trainer/hooks/loggers/checkpoint tests (strategy mirrors reference
test/test_trainer.py: hook registration + end-to-end loop, logger round-trips,
checkpoint save/restore equivalence)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.checkpoint import Checkpoint, GlobalRNGState, JSONAdapter
from rl_tpu.collectors import Collector
from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.modules import MLP, Categorical, ProbabilisticActor, TDModule, ValueOperator
from rl_tpu.objectives import ClipPPOLoss
from rl_tpu.record import CSVLogger, NullLogger, get_logger
from rl_tpu.trainers import (
    CountFramesLog,
    EarlyStopping,
    Evaluator,
    LogScalar,
    LogTiming,
    OnPolicyConfig,
    OnPolicyProgram,
    Trainer,
)

KEY = jax.random.key(0)


def make_program(num_envs=4, frames=64):
    env = TransformedEnv(VmapEnv(CartPoleEnv(), num_envs), RewardSum())
    actor = ProbabilisticActor(
        TDModule(MLP(out_features=2), ["observation"], ["logits"]),
        Categorical,
        dist_keys=("logits",),
    )
    critic = ValueOperator(MLP(out_features=1))
    loss = ClipPPOLoss(actor, critic)
    coll = Collector(env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=frames)
    program = OnPolicyProgram(coll, loss, OnPolicyConfig(num_epochs=1, minibatch_size=32))
    return env, actor, program


class TestTrainer:
    @pytest.mark.slow
    def test_loop_with_hooks(self, tmp_path):
        env, actor, program = make_program()
        logger = CSVLogger("t1", log_dir=str(tmp_path))
        trainer = Trainer(program, total_steps=3, logger=logger)
        trainer.register_op("post_step", LogScalar())
        trainer.register_op("post_step", CountFramesLog(interval=1))
        trainer.register_op("post_step", LogTiming(interval=1))
        ts = trainer.train(0)
        assert trainer.step_count == 3
        assert trainer.collected_frames == 192
        files = os.listdir(os.path.join(str(tmp_path), "t1"))
        assert any(f.startswith("train_loss") for f in files)
        assert any(f.startswith("train_fps") for f in files)

    @pytest.mark.slow
    def test_early_stopping(self):
        env, actor, program = make_program()
        trainer = Trainer(program, total_steps=50)
        # reward_mean for CartPole is always 1.0 -> stops immediately
        trainer.register_op("post_step", EarlyStopping(metric="reward_mean", threshold=0.5))
        trainer.train(0)
        assert trainer.step_count == 1

    @pytest.mark.slow
    def test_evaluator_hook(self, tmp_path):
        env, actor, program = make_program()
        logger = CSVLogger("t2", log_dir=str(tmp_path))
        trainer = Trainer(program, total_steps=2, logger=logger)
        trainer.register_op(
            "post_step",
            Evaluator(env, lambda p, td, k: actor(p["actor"], td, k), interval=1, max_steps=8),
        )
        trainer.train(0)
        files = os.listdir(os.path.join(str(tmp_path), "t2"))
        assert any(f.startswith("eval_reward_mean") for f in files)

    def test_bad_stage_raises(self):
        _, _, program = make_program()
        trainer = Trainer(program, total_steps=1)
        with pytest.raises(ValueError):
            trainer.register_op("nope", lambda t: None)


class TestCheckpoint:
    @pytest.mark.slow
    def test_roundtrip_train_state(self, tmp_path):
        _, _, program = make_program()
        ts = program.init(KEY)
        step = jax.jit(program.train_step)
        ts, _ = step(ts)

        ckpt = Checkpoint(str(tmp_path / "ck"))
        holder = {"ts": ts}
        ckpt.register("train_state", lambda: holder["ts"], lambda v: holder.update(ts=v),
                      template=lambda: holder["ts"])
        ckpt.save(step=1)

        # run forward, then restore and check we reproduce the same next step
        ts2, m2 = step(ts)
        holder["ts"] = ts2  # clobber
        ckpt.load(step=1)
        ts_r = holder["ts"]
        ts3, m3 = step(ts_r)
        np.testing.assert_allclose(
            float(m2["loss"]), float(m3["loss"]), rtol=1e-5
        )

    @pytest.mark.slow
    def test_trainer_checkpoint_cadence(self, tmp_path):
        _, _, program = make_program()
        ckpt = Checkpoint(str(tmp_path / "ck2"))
        trainer = Trainer(program, total_steps=4, checkpoint=ckpt, checkpoint_interval=2)
        trainer.train(0)
        assert ckpt.latest_step() == 4
        assert sorted(os.listdir(str(tmp_path / "ck2"))) == ["step_2", "step_4"]

    def test_migration(self, tmp_path):
        import json

        ckpt = Checkpoint(str(tmp_path / "ck3"))
        state = {"v": 1}
        ckpt.register("counters", lambda: state, lambda v: state.update(v), adapter=JSONAdapter())
        d = ckpt.save(step=1)
        # rewrite as an old schema version
        meta = json.load(open(os.path.join(d, "meta.json")))
        meta["schema_version"] = 0
        json.dump(meta, open(os.path.join(d, "meta.json"), "w"))
        with pytest.raises(RuntimeError):
            ckpt.load(step=1)
        migrated = []
        ckpt.register_migration(0, lambda path: migrated.append(path))
        ckpt.load(step=1)
        assert migrated
        # non-idempotent safety: second load must NOT re-run the migration
        ckpt.load(step=1)
        assert len(migrated) == 1

    @pytest.mark.slow
    def test_trainer_restore_resumes_counters(self, tmp_path):
        _, _, program = make_program()
        ckpt = Checkpoint(str(tmp_path / "ck4"))
        trainer = Trainer(program, total_steps=3, checkpoint=ckpt, checkpoint_interval=3)
        trainer.train(0)
        assert trainer.step_count == 3

        # fresh trainer resumes: counters restored, runs only the remainder
        ckpt2 = Checkpoint(str(tmp_path / "ck4"))
        trainer2 = Trainer(program, total_steps=5, checkpoint=ckpt2, checkpoint_interval=100)
        trainer2.restore()
        assert trainer2.step_count == 3
        assert trainer2.collected_frames == 192
        trainer2.train()
        assert trainer2.step_count == 5

    def test_restore_without_checkpoint_raises(self):
        _, _, program = make_program()
        with pytest.raises(RuntimeError):
            Trainer(program, total_steps=1).restore()

    def test_rng_capture(self):
        state = GlobalRNGState.get()
        a = np.random.rand()
        GlobalRNGState.set(state)
        b = np.random.rand()
        assert a == b


class TestLoggers:
    def test_csv_logger(self, tmp_path):
        lg = CSVLogger("exp", log_dir=str(tmp_path))
        lg.log_scalar("a/b", 1.5, step=10)
        lg.log_hparams({"lr": 3e-4})
        lg.close()
        with open(os.path.join(str(tmp_path), "exp", "a_b.csv")) as f:
            assert f.read().strip() == "10,1.5"

    @pytest.mark.slow
    def test_tensorboard_logger(self, tmp_path):
        lg = get_logger("tensorboard", "exp", log_dir=str(tmp_path))
        lg.log_scalar("x", 2.0, step=1)
        lg.log_histogram("h", np.random.randn(100), step=1)
        assert os.listdir(os.path.join(str(tmp_path), "exp"))

    def test_get_logger_unknown(self):
        with pytest.raises(ValueError):
            get_logger("nope", "x")

    def test_null_logger(self):
        NullLogger().log_scalars({"a": 1.0}, step=0)


class TestPreemption:
    """SIGTERM-aware checkpoint + auto-resume (SURVEY §5 failure recovery)."""

    @pytest.mark.slow
    def test_sigterm_checkpoints_and_resumes(self, tmp_path):
        import os
        import signal

        from rl_tpu.trainers.resilience import PreemptionHandler

        _, _, program = make_program()
        ckpt = Checkpoint(str(tmp_path / "pk"))
        trainer = Trainer(program, total_steps=50, checkpoint=ckpt)
        handler = PreemptionHandler().install()

        def send_sigterm(t, m=None):
            if t.step_count == 2:
                os.kill(os.getpid(), signal.SIGTERM)

        # sender registered BEFORE the handler: a real SIGTERM lands during
        # the jitted step, i.e. before post_step hooks run
        trainer.register_op("post_step", send_sigterm)
        trainer.register_op("post_step", handler)
        try:
            trainer.train(0)
        finally:
            handler.uninstall()
        # stopped at the preemption point with a checkpoint on disk
        assert trainer.step_count == 2
        assert handler.preempted
        assert ckpt.latest_step() == 2

        # fresh process analog: auto_resume picks up at step 2, runs 3 more
        ckpt2 = Checkpoint(str(tmp_path / "pk"))
        trainer2 = Trainer(program, total_steps=5, checkpoint=ckpt2, auto_resume=True)
        trainer2.train(0)
        assert trainer2.step_count == 5

    def test_programmatic_preempt_without_signal(self, tmp_path):
        from rl_tpu.trainers.resilience import PreemptionHandler

        _, _, program = make_program()
        ckpt = Checkpoint(str(tmp_path / "pk2"))
        trainer = Trainer(program, total_steps=50, checkpoint=ckpt)
        handler = PreemptionHandler()  # no signal install needed
        trainer.register_op(
            "post_step", lambda t, m=None: handler.preempt() if t.step_count == 1 else None
        )
        trainer.register_op("post_step", handler)
        trainer.train(0)
        assert trainer.step_count == 1 and ckpt.latest_step() == 1

    def test_auto_resume_without_checkpoint_is_noop(self):
        _, _, program = make_program()
        trainer = Trainer(program, total_steps=1, auto_resume=True)
        trainer.train(0)  # no checkpoint configured -> trains from scratch
        assert trainer.step_count == 1
