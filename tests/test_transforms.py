"""Transform tests (strategy mirrors reference test/transforms/: per-transform
behavior + spec agreement, verified through check_env_specs on the composed
stack)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import ArrayDict

from rl_tpu.envs import (
    ActionScaling,
    CatFrames,
    CatTensors,
    Compose,
    InitTracker,
    ObservationNorm,
    PendulumEnv,
    RewardClipping,
    RewardScaling,
    RewardSum,
    StepCounter,
    TransformedEnv,
    UnsqueezeTransform,
    VmapEnv,
    check_env_specs,
    rollout,
)
from rl_tpu.testing import ContinuousActionMock, CountingEnv, MultiKeyCountingEnv

KEY = jax.random.key(0)


STACKS = [
    lambda: TransformedEnv(CountingEnv(), RewardScaling(loc=1.0, scale=2.0)),
    lambda: TransformedEnv(CountingEnv(), RewardSum()),
    lambda: TransformedEnv(CountingEnv(), StepCounter(max_steps=4)),
    lambda: TransformedEnv(CountingEnv(), InitTracker()),
    lambda: TransformedEnv(PendulumEnv(), CatFrames(n=4)),
    lambda: TransformedEnv(
        PendulumEnv(), ObservationNorm(loc=0.0, scale=2.0, in_keys=["observation"])
    ),
    lambda: TransformedEnv(
        MultiKeyCountingEnv(), CatTensors(in_keys=["obs_vec", ("nested", "obs_img")])
    ),
    lambda: TransformedEnv(
        ContinuousActionMock(), ActionScaling(low=-2.0, high=2.0)
    ),
    lambda: TransformedEnv(
        CountingEnv(),
        Compose(RewardScaling(scale=0.5), RewardSum(), StepCounter(), InitTracker()),
    ),
]


@pytest.mark.parametrize("make", STACKS, ids=lambda m: repr(m().transform)[:48])
class TestConformance:
    @pytest.mark.slow
    def test_check_env_specs(self, make):
        check_env_specs(make(), KEY)

    @pytest.mark.slow
    def test_vmapped(self, make):
        check_env_specs(VmapEnv(make(), 3), KEY)


class TestBehavior:
    def test_reward_scaling(self):
        env = TransformedEnv(CountingEnv(), RewardScaling(loc=1.0, scale=2.0))
        steps = rollout(env, KEY, max_steps=3)
        np.testing.assert_allclose(np.asarray(steps["next", "reward"]), 3.0 * np.ones(3))

    def test_reward_clipping(self):
        env = TransformedEnv(CountingEnv(), RewardClipping(-0.5, 0.5))
        steps = rollout(env, KEY, max_steps=3)
        np.testing.assert_allclose(np.asarray(steps["next", "reward"]), 0.5 * np.ones(3))

    def test_reward_sum_accumulates_and_resets(self):
        env = TransformedEnv(CountingEnv(max_count=3), RewardSum())
        steps = rollout(env, KEY, max_steps=7)
        ep = np.asarray(steps["next", "episode_reward"])
        np.testing.assert_allclose(ep, [1, 2, 3, 1, 2, 3, 1])

    def test_step_counter_truncates(self):
        env = TransformedEnv(CountingEnv(max_count=100), StepCounter(max_steps=4))
        steps = rollout(env, KEY, max_steps=9)
        trunc = np.asarray(steps["next", "truncated"])
        np.testing.assert_array_equal(trunc, [0, 0, 0, 1, 0, 0, 0, 1, 0])
        counts = np.asarray(steps["next", "step_count"])
        np.testing.assert_array_equal(counts, [1, 2, 3, 4, 1, 2, 3, 4, 1])

    def test_init_tracker(self):
        env = TransformedEnv(CountingEnv(max_count=3), InitTracker())
        state, td = env.reset(KEY)
        assert bool(td["is_init"])
        steps = rollout(env, KEY, max_steps=6)
        # is_init in "next" flags the step AFTER done as init
        is_init = np.asarray(steps["next", "is_init"])
        np.testing.assert_array_equal(is_init, [0, 0, 1, 0, 0, 1])

    def test_cat_frames_stacks_history(self):
        env = TransformedEnv(CountingEnv(max_count=100), CatFrames(n=3))
        steps = rollout(env, KEY, max_steps=4)
        obs = np.asarray(steps["next", "observation"])
        assert obs.shape == (4, 3)
        np.testing.assert_allclose(obs[0], [0, 0, 1])  # padded with reset obs
        np.testing.assert_allclose(obs[3], [2, 3, 4])

    def test_obs_norm(self):
        env = TransformedEnv(
            CountingEnv(max_count=100),
            ObservationNorm(loc=1.0, scale=2.0, in_keys=["observation"]),
        )
        steps = rollout(env, KEY, max_steps=2)
        np.testing.assert_allclose(
            np.asarray(steps["next", "observation"]).squeeze(-1), [0.0, 0.5]
        )

    def test_action_scaling_maps_domain(self):
        base = ContinuousActionMock()
        env = TransformedEnv(base, ActionScaling(low=-2.0, high=2.0))
        spec = env.action_spec
        assert float(np.asarray(spec.low).max()) == -1.0
        state, td = env.reset(KEY)
        td = td.set("action", jnp.ones((base.act_dim,)))  # +1 -> high (=2)
        _, out = env.step(state, td)
        # root keeps the policy-side action
        np.testing.assert_allclose(np.asarray(out["action"]), 1.0)

    def test_cat_tensors(self):
        env = TransformedEnv(
            MultiKeyCountingEnv(),
            CatTensors(in_keys=["obs_vec", ("nested", "obs_img")]),
        )
        state, td = env.reset(KEY)
        assert td["observation_vector"].shape == (7,)
        assert "obs_vec" not in td

    def test_unsqueeze(self):
        env = TransformedEnv(
            CountingEnv(), UnsqueezeTransform(axis=-1, in_keys=["observation"])
        )
        state, td = env.reset(KEY)
        assert td["observation"].shape == (1, 1)

    def test_compose_order_and_jit(self):
        env = TransformedEnv(
            CountingEnv(max_count=3),
            Compose(RewardScaling(scale=2.0), RewardSum()),
        )
        f = jax.jit(lambda k: rollout(env, k, max_steps=6))
        steps = f(KEY)
        ep = np.asarray(steps["next", "episode_reward"])
        np.testing.assert_allclose(ep, [2, 4, 6, 2, 4, 6])


class TestWrappersAndPooling:
    @pytest.mark.slow
    def test_frame_skip_sums_rewards(self):
        from rl_tpu.envs import FrameSkipEnv

        env = FrameSkipEnv(CountingEnv(max_count=100), skip=4)
        check_env_specs(env, KEY)
        steps = rollout(env, KEY, max_steps=3)
        # each outer step advances 4, reward 4x1
        np.testing.assert_allclose(np.asarray(steps["next", "reward"]), 4.0)
        np.testing.assert_allclose(
            np.asarray(steps["next", "observation"]).squeeze(-1), [4, 8, 12]
        )

    def test_frame_skip_stops_at_done(self):
        from rl_tpu.envs import FrameSkipEnv

        env = FrameSkipEnv(CountingEnv(max_count=2), skip=4)
        state, td = env.reset(KEY)
        td = env.rand_action(td, KEY)
        _, out = env.step(state, td)
        # episode ends at count 2 -> only 2 rewards accumulate
        assert float(out["next", "reward"]) == 2.0
        assert bool(out["next", "done"])

    @pytest.mark.slow
    def test_noop_reset_advances_state(self):
        from rl_tpu.envs import NoopResetEnv

        env = NoopResetEnv(CountingEnv(max_count=100), noop_max=5)
        check_env_specs(env, KEY)
        state, td = env.reset(KEY)
        c = float(td["observation"][0])
        assert 1 <= c <= 5, c

    def test_time_max_pool(self):
        from rl_tpu.envs import TimeMaxPool

        class Alternating(CountingEnv):
            def _step(self, state, action, key):
                state, obs, r, term, trunc = super()._step(state, action, key)
                # even steps produce 10, odd steps produce count
                c = state["count"]
                val = jnp.where(c % 2 == 0, 10.0, obs["observation"][0])
                return state, ArrayDict(observation=val[None]), r, term, trunc

        env = TransformedEnv(Alternating(max_count=100), TimeMaxPool(T=2))
        steps = rollout(env, KEY, max_steps=6)
        obs = np.asarray(steps["next", "observation"]).squeeze(-1)
        # max over {current, previous} -> 10 persists across odd steps
        assert (obs >= 9.0).sum() >= 4

    @pytest.mark.slow
    def test_noop_reset_never_returns_done(self):
        from rl_tpu.envs import NoopResetEnv

        # max_count=2 < noop_max: reset must stop before terminating
        env = NoopResetEnv(CountingEnv(max_count=2), noop_max=6)
        for s in range(5):
            state, td = env.reset(jax.random.key(s))
            assert not bool(td["done"]), "reset returned a done state"
            assert float(td["observation"][0]) <= 1.0  # stops pre-terminal


class TestPixelRender:
    """Device-side state->pixels rendering (round-5; reference analog:
    gym from_pixels=True host render, torchrl/envs/libs/gym.py)."""

    def test_spec_and_rollout(self):
        from rl_tpu.envs import CartPoleEnv, PixelRender, cartpole_pixels

        env = TransformedEnv(
            VmapEnv(CartPoleEnv(), 3),
            PixelRender(cartpole_pixels, shape=(84, 84, 4), keep_obs=False),
        )
        check_env_specs(env, jax.random.key(0))
        state, td = env.reset(jax.random.key(1))
        assert td["pixels"].shape == (3, 84, 84, 4)
        assert "observation" not in td
        img = np.asarray(td["pixels"])
        assert img.min() >= 0.0 and img.max() <= 1.0
        assert img[..., 1].max() > 0.5  # the pole is actually drawn

    def test_render_moves_with_state(self):
        from rl_tpu.envs import CartPoleEnv, PixelRender, cartpole_pixels

        env = TransformedEnv(
            CartPoleEnv(), PixelRender(cartpole_pixels, shape=(84, 84, 4))
        )
        state, td = env.reset(jax.random.key(0))
        frames = rollout(env, jax.random.key(1), None, max_steps=8)
        f = np.asarray(frames["pixels"])
        assert f.shape == (8, 84, 84, 4)
        # the cart/pole channels change as the state evolves
        assert np.abs(f[0, ..., :2] - f[-1, ..., :2]).max() > 0.01

    def test_shape_mismatch_raises(self):
        from rl_tpu.envs import CartPoleEnv, PixelRender, cartpole_pixels

        env = TransformedEnv(
            CartPoleEnv(), PixelRender(cartpole_pixels, shape=(64, 64, 2))
        )
        with pytest.raises(ValueError, match="declared spec shape"):
            env.reset(jax.random.key(0))

    def test_partial_render_fn_matches_declared_shape(self):
        import functools

        from rl_tpu.envs import CartPoleEnv, PixelRender, cartpole_pixels

        env = TransformedEnv(
            CartPoleEnv(),
            PixelRender(
                functools.partial(cartpole_pixels, size=32, channels=2),
                shape=(32, 32, 2),
            ),
        )
        check_env_specs(env, jax.random.key(0))
