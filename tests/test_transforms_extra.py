"""Second-wave transform tests (strategy mirrors reference test/transforms/):
per-transform behavior + spec agreement via check_env_specs, plus the
step-structure wrappers (MultiAction, ConditionalSkip) and replay-side
transforms (Reward2Go, BurnIn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import ArrayDict, BurnInTransform, Reward2GoTransform
from rl_tpu.data.specs import Unbounded
from rl_tpu.envs import (
    ActionDiscretizer,
    ActionMask,
    BinarizeReward,
    ClipTransform,
    Compose,
    ConditionalSkipEnv,
    EndOfLifeTransform,
    ExcludeTransform,
    FiniteCheck,
    Hash,
    LineariseRewards,
    ModuleTransform,
    MultiActionEnv,
    PermuteTransform,
    SelectTransform,
    SignTransform,
    StackTransform,
    TensorDictPrimer,
    Timer,
    TrajCounter,
    TransformedEnv,
    VmapEnv,
    check_env_specs,
    rollout,
)
from rl_tpu.testing import (
    ContinuousActionMock,
    CountingEnv,
    LivesCountingEnv,
    MaskedActionMock,
    MultiKeyCountingEnv,
)

KEY = jax.random.key(0)


STACKS = [
    lambda: TransformedEnv(CountingEnv(), BinarizeReward()),
    lambda: TransformedEnv(CountingEnv(), SignTransform()),
    lambda: TransformedEnv(CountingEnv(), ClipTransform(low=-0.5, high=0.5)),
    lambda: TransformedEnv(CountingEnv(), ExcludeTransform()),
    lambda: TransformedEnv(CountingEnv(), SelectTransform("observation")),
    lambda: TransformedEnv(CountingEnv(), TrajCounter()),
    lambda: TransformedEnv(
        CountingEnv(), TensorDictPrimer({"hidden": Unbounded(shape=(3,))})
    ),
    lambda: TransformedEnv(LivesCountingEnv(), EndOfLifeTransform()),
    lambda: TransformedEnv(MaskedActionMock(), ActionMask()),
    lambda: TransformedEnv(ContinuousActionMock(), ActionDiscretizer(num_intervals=7)),
    lambda: TransformedEnv(CountingEnv(), Hash(in_keys=["observation"])),
    lambda: TransformedEnv(
        CountingEnv(), ModuleTransform(lambda x: 2.0 * x, in_keys=["observation"])
    ),
    lambda: TransformedEnv(CountingEnv(), FiniteCheck()),
    lambda: TransformedEnv(
        MultiKeyCountingEnv(), StackTransform(in_keys=["obs_vec"], out_key="stacked")
    ),
]


@pytest.mark.parametrize("make", STACKS, ids=lambda m: repr(m().transform)[:48])
@pytest.mark.slow
def test_check_env_specs(make):
    check_env_specs(make(), KEY)


def test_select_exclude_keys():
    env = TransformedEnv(MultiKeyCountingEnv(), ExcludeTransform(("nested", "obs_img")))
    _, td = env.reset(KEY)
    assert ("nested", "obs_img") not in td
    env = TransformedEnv(MultiKeyCountingEnv(), SelectTransform("obs_vec"))
    _, td = env.reset(KEY)
    assert "obs_vec" in td and ("nested", "obs_img") not in td
    assert "done" in td  # protected keys survive


def test_permute_hwc_to_chw():
    t = PermuteTransform(dims=(-1, -3, -2), in_keys=["img"])
    td = ArrayDict(img=jnp.zeros((5, 8, 6, 3)), done=jnp.zeros((5,), bool))
    _, out = t.step(ArrayDict(), td)
    assert out["img"].shape == (5, 3, 8, 6)
    spec = t.transform_observation_spec(
        __import__("rl_tpu.data", fromlist=["Composite"]).Composite(
            img=Unbounded(shape=(8, 6, 3))
        )
    )
    assert spec["img"].shape == (3, 8, 6)


def test_stack_transform_shape():
    env = TransformedEnv(
        MultiKeyCountingEnv(),
        StackTransform(in_keys=["obs_vec"], out_key="stacked", del_keys=False),
    )
    _, td = env.reset(KEY)
    assert td["stacked"].shape[-1] == 1
    assert np.allclose(np.asarray(td["stacked"][..., 0]), np.asarray(td["obs_vec"]))


@pytest.mark.slow
def test_reward_shaping_values():
    env = TransformedEnv(CountingEnv(), BinarizeReward())
    batch = rollout(env, KEY, max_steps=4)
    assert np.all(np.asarray(batch["next", "reward"]) == 1.0)

    env = TransformedEnv(CountingEnv(), SignTransform())
    batch = rollout(env, KEY, max_steps=4)
    assert np.all(np.asarray(batch["next", "reward"]) == 1.0)

    env = TransformedEnv(CountingEnv(), ClipTransform(low=-0.25, high=0.25))
    batch = rollout(env, KEY, max_steps=4)
    assert np.all(np.asarray(batch["next", "reward"]) == 0.25)


def test_linearise_rewards():
    t = LineariseRewards(weights=[1.0, 2.0])
    td = ArrayDict(reward=jnp.asarray([1.0, 3.0]), done=jnp.asarray(False))
    _, out = t.step(ArrayDict(), td)
    assert float(out["reward"]) == 7.0
    spec = t.transform_reward_spec(Unbounded(shape=(2,)))
    assert spec.shape == ()


def test_primer_defaults_and_carry():
    env = TransformedEnv(
        CountingEnv(), TensorDictPrimer({"hidden": Unbounded(shape=(3,))})
    )
    _, td = env.reset(KEY)
    assert td["hidden"].shape == (3,)
    assert np.all(np.asarray(td["hidden"]) == 0)
    batch = rollout(env, KEY, max_steps=3)
    assert batch["next", "hidden"].shape == (3, 3)


@pytest.mark.slow
def test_traj_counter_unique_ids():
    env = VmapEnv(CountingEnv(max_count=3), 4)
    env = TransformedEnv(env, TrajCounter())
    batch = rollout(env, KEY, max_steps=10)
    ids = np.asarray(batch["next", "traj_count"])  # [T, B]
    done = np.asarray(batch["next", "done"])
    # ids within an episode are constant; after a done the id changes and is fresh
    seen = set()
    for b in range(4):
        cur = ids[0, b]
        for t in range(10):
            assert ids[t, b] == cur or done[t - 1, b]
            cur = ids[t, b]
        for t in range(10):
            if done[t, b] and t + 1 < 10:
                nxt = ids[t + 1, b]
                assert nxt != ids[t, b]
        for t in range(10):
            seen.add((b, int(ids[t, b])))
    # global uniqueness: an id never appears in two different env slots
    by_id = {}
    for b, i in seen:
        assert by_id.setdefault(i, b) == b


def test_timer_measures_nonnegative():
    env = TransformedEnv(CountingEnv(), Timer())
    batch = rollout(env, KEY, max_steps=3)
    assert np.all(np.asarray(batch["next", "time_step"]) >= 0)


def test_end_of_life_flag():
    env = TransformedEnv(LivesCountingEnv(lives=3, steps_per_life=2), EndOfLifeTransform())
    batch = rollout(env, KEY, max_steps=6)
    eol = np.asarray(batch["next", "end_of_life"])
    done = np.asarray(batch["next", "done"])
    # life losses at steps 2 and 4 (0-indexed 1, 3); termination at step 6
    assert eol[1] and eol[3]
    assert not eol[0] and not eol[2]
    assert done[5] and not eol[5]  # terminal step is done, not eol


def test_end_of_life_done_promotion():
    env = TransformedEnv(
        LivesCountingEnv(lives=3, steps_per_life=2),
        EndOfLifeTransform(done_on_life_loss=True),
    )
    batch = rollout(env, KEY, max_steps=6)
    done = np.asarray(batch["next", "done"])
    assert done[1]  # first life loss now ends the episode


def test_action_mask_rand_action_legal():
    env = TransformedEnv(MaskedActionMock(n_actions=6, max_count=5), ActionMask())
    batch = rollout(env, KEY, max_steps=5)
    acts = np.asarray(batch["action"])
    # at step t the mask allows actions <= t (count before the step)
    for t in range(5):
        assert acts[t] <= t


@pytest.mark.slow
def test_action_discretizer_roundtrip():
    base = ContinuousActionMock()
    env = TransformedEnv(base, ActionDiscretizer(num_intervals=5))
    spec = env.action_spec
    assert spec.shape == (base.act_dim,)
    batch = rollout(env, KEY, max_steps=4)
    acts = np.asarray(batch["action"])
    assert acts.dtype in (np.int32, np.int64)
    assert acts.min() >= 0 and acts.max() < 5


def test_hash_deterministic():
    t = Hash(in_keys=["observation"])
    td1 = ArrayDict(observation=jnp.asarray([1.0, 2.0]), done=jnp.asarray(False))
    td2 = ArrayDict(observation=jnp.asarray([1.0, 2.0]), done=jnp.asarray(False))
    td3 = ArrayDict(observation=jnp.asarray([1.0, 3.0]), done=jnp.asarray(False))
    _, h1 = t.step(ArrayDict(), td1)
    _, h2 = t.step(ArrayDict(), td2)
    _, h3 = t.step(ArrayDict(), td3)
    assert int(h1["observation_hash"]) == int(h2["observation_hash"])
    assert int(h1["observation_hash"]) != int(h3["observation_hash"])


def test_module_transform_applies():
    env = TransformedEnv(
        CountingEnv(), ModuleTransform(lambda x: 3.0 * x, in_keys=["observation"])
    )
    batch = rollout(env, KEY, max_steps=3)
    obs = np.asarray(batch["next", "observation"])
    assert np.allclose(obs[:, 0], 3.0 * np.arange(1, 4))


@pytest.mark.slow
def test_finite_check_flags_nan():
    env = TransformedEnv(
        CountingEnv(),
        Compose(
            ModuleTransform(
                lambda x: jnp.where(x > 1.5, jnp.nan, x), in_keys=["observation"]
            ),
            FiniteCheck(),
        ),
    )
    batch = rollout(env, KEY, max_steps=4)
    ok = np.asarray(batch["next", "finite_ok"])
    assert ok[0] and not ok[2]


def test_multi_action_env_sums_rewards():
    env = MultiActionEnv(CountingEnv(max_count=10), num_actions=3)
    assert env.action_spec.shape == (3,)
    batch = rollout(env, KEY, max_steps=2)
    # each macro step advances 3 counts, reward 3.0
    assert np.allclose(np.asarray(batch["next", "reward"]), 3.0)
    obs = np.asarray(batch["next", "observation"])
    assert np.allclose(obs[:, 0], [3.0, 6.0])


def test_multi_action_env_stops_at_done():
    env = MultiActionEnv(CountingEnv(max_count=2), num_actions=5)
    batch = rollout(env, KEY, max_steps=1, auto_reset=False)
    # only 2 of 5 sub-steps yield reward before termination
    assert float(batch["next", "reward"][0]) == 2.0
    assert bool(batch["next", "done"][0])


def test_conditional_skip_freezes_state():
    # skip every step where the current count is odd
    def cond(td):
        return (td["observation"][..., 0].astype(jnp.int32) % 2) == 1

    env = ConditionalSkipEnv(CountingEnv(max_count=100), cond)
    batch = rollout(env, KEY, max_steps=6)
    obs = np.asarray(batch["next", "observation"][:, 0])
    rew = np.asarray(batch["next", "reward"])
    # counts: 1 (stepped), then frozen at 1 forever (cond is True at count 1)
    assert obs[0] == 1.0
    assert np.all(obs[1:] == 1.0)
    assert rew[0] == 1.0 and np.all(rew[1:] == 0.0)


@pytest.mark.slow
def test_reward2go_matches_bruteforce():
    T = 8
    key = jax.random.key(3)
    reward = jax.random.normal(key, (T,))
    done = jnp.zeros((T,), bool).at[3].set(True)
    batch = ArrayDict(next=ArrayDict(reward=reward, done=done))
    out = Reward2GoTransform(gamma=0.9)(batch)
    rtg = np.asarray(out["reward_to_go"])
    expect = np.zeros(T)
    acc = 0.0
    for t in reversed(range(T)):
        acc = float(reward[t]) + 0.9 * acc * (0.0 if done[t] else 1.0)
        # reward-to-go INCLUDES own reward; reset AFTER a done step
        expect[t] = float(reward[t]) + 0.9 * (expect[t + 1] if t + 1 < T and not done[t] else 0.0)
    assert np.allclose(rtg, expect, atol=1e-5)


@pytest.mark.slow
def test_burn_in_transform():
    from rl_tpu.modules.rnn import GRUModule

    m = GRUModule(input_size=3, hidden_size=4, in_key="obs", out_key="embed")
    B, T = 2, 6
    obs = jax.random.normal(jax.random.key(1), (B, T, 3))
    td = ArrayDict(obs=obs, is_init=jnp.zeros((B, T), bool))
    params = m.init(jax.random.key(2), td)

    burn = BurnInTransform(m, params, burn_in=2)
    out = burn(td)
    assert out["obs"].shape == (B, T - 2, 3)
    ck = m._carry_keys()
    assert ck[0] in out and out[ck[0]].shape == (B, 4)

    # burned-in carry changes the sequence output vs zero-carry
    with_carry = m(params, out)["embed"]
    zero_carry = m(params, out.exclude(*ck))["embed"]
    assert not np.allclose(np.asarray(with_carry), np.asarray(zero_carry))


@pytest.mark.slow
def test_traj_counter_root_ids_after_autoreset():
    # regression: the root (carried) traj_count after an auto-reset must be
    # the freshly ASSIGNED global id, not a fresh-init arange id
    env = VmapEnv(CountingEnv(max_count=2), 3)
    env = TransformedEnv(env, TrajCounter())
    batch = rollout(env, KEY, max_steps=6)
    root_ids = np.asarray(batch["traj_count"])  # [T, B]
    next_ids = np.asarray(batch["next", "traj_count"])
    done = np.asarray(batch["next", "done"])
    for b in range(3):
        for t in range(5):
            if done[t, b]:
                assert root_ids[t + 1, b] not in next_ids[: t + 1, b]
            else:
                assert root_ids[t + 1, b] == next_ids[t, b]


@pytest.mark.slow
def test_multi_action_batch_major_layout():
    # regression: spec-shaped (batch-major) actions must drive the macro scan
    env = MultiActionEnv(VmapEnv(CountingEnv(max_count=100), 2), num_actions=3)
    spec = env.action_spec
    acts = spec.rand(KEY, env.batch_shape)
    assert acts.shape == (2, 3)
    batch = rollout(env, KEY, max_steps=2)
    obs = np.asarray(batch["next", "observation"])
    assert np.allclose(obs[:, :, 0], [[3.0, 3.0], [6.0, 6.0]])


@pytest.mark.slow
def test_permute_default_keys_skips_flags():
    # regression: default in_keys must not permute reward/done leaves
    class ImgEnv(CountingEnv):
        @property
        def observation_spec(self):
            from rl_tpu.data import Composite

            return Composite(pixels=Unbounded(shape=(4, 6, 3)))

        def _reset(self, key):
            state, _ = super()._reset(key)
            return state, ArrayDict(pixels=jnp.zeros((4, 6, 3)))

        def _step(self, state, action, key):
            state, _, r, te, tr = super()._step(state, action, key)
            c = state["count"].astype(jnp.float32)
            return state, ArrayDict(pixels=jnp.full((4, 6, 3), c)), r, te, tr

    env = TransformedEnv(ImgEnv(), PermuteTransform(dims=(-1, -3, -2)))
    check_env_specs(env, KEY)
    batch = rollout(env, KEY, max_steps=2)
    assert batch["next", "pixels"].shape[-3:] == (3, 4, 6)


def test_action_discretizer_inv_without_spec_read():
    # regression: inv() must work even if env.action_spec is never read
    env = TransformedEnv(ContinuousActionMock(), ActionDiscretizer(num_intervals=4))
    state, td = env.reset(KEY)
    td = td.set("action", jnp.zeros((2,), jnp.int32))
    _, out = env.step(state, td)
    assert "next" in out


class TestThirdWave:
    """extra2.py transforms (reference TargetReturn/Crop/
    DiscreteActionProjection/UnaryTransform/RandomTruncationTransform)."""

    def test_target_return_reduce(self):
        from rl_tpu.envs import CartPoleEnv, TargetReturn, TransformedEnv

        env = TransformedEnv(CartPoleEnv(), TargetReturn(5.0))
        state, td = env.reset(KEY)
        assert float(td["target_return"]) == 5.0
        td = td.set("action", jnp.asarray(0))
        state, out = env.step(state, td)
        # CartPole reward is 1 -> target drops to 4
        assert float(out["next"]["target_return"]) == 4.0
        check_env_specs(env)

    def test_target_return_constant(self):
        from rl_tpu.envs import CartPoleEnv, TargetReturn, TransformedEnv

        env = TransformedEnv(CartPoleEnv(), TargetReturn(3.0, mode="constant"))
        state, td = env.reset(KEY)
        td = td.set("action", jnp.asarray(0))
        _, out = env.step(state, td)
        assert float(out["next"]["target_return"]) == 3.0

    def test_crop(self):
        from rl_tpu.envs import Crop

        t = Crop(8, 6, top=2, left=1)
        td = ArrayDict(pixels=jnp.arange(16 * 16 * 3).reshape(16, 16, 3))
        _, out = t.step(ArrayDict(), td)
        assert out["pixels"].shape == (8, 6, 3)
        np.testing.assert_array_equal(
            np.asarray(out["pixels"]), np.asarray(td["pixels"])[2:10, 1:7]
        )

    def test_discrete_action_projection(self):
        from rl_tpu.envs import CartPoleEnv, DiscreteActionProjection, TransformedEnv

        env = TransformedEnv(CartPoleEnv(), DiscreteActionProjection(6))
        assert env.action_spec.n == 6
        state, td = env.reset(KEY)
        # action 5 folds to 5 % 2 = 1 — must step without error
        _, out = env.step(state, td.set("action", jnp.asarray(5)))
        assert bool(out["next"]["done"]) in (True, False)
        check_env_specs(env)

    def test_unary(self):
        from rl_tpu.envs import CartPoleEnv, TransformedEnv, UnaryTransform

        env = TransformedEnv(
            CartPoleEnv(), UnaryTransform("observation", "obs_sq", lambda x: x**2)
        )
        state, td = env.reset(KEY)
        np.testing.assert_allclose(
            np.asarray(td["obs_sq"]), np.asarray(td["observation"]) ** 2, rtol=1e-6
        )
        check_env_specs(env)

    def test_random_truncation_statistics(self):
        from rl_tpu.envs import PendulumEnv, RandomTruncationTransform, TransformedEnv, VmapEnv

        env = TransformedEnv(
            VmapEnv(PendulumEnv(), 64), RandomTruncationTransform(p=0.5, seed=1)
        )
        state, td = env.reset(KEY)
        td = td.set("action", jnp.zeros((64, 1)))
        _, out = env.step(state, td)
        frac = float(out["next"]["truncated"].mean())
        assert 0.25 < frac < 0.75  # ~Bernoulli(0.5)

    def test_random_truncation_decorrelated_under_vmap(self):
        """transform INSIDE VmapEnv: lanes must not truncate in lockstep."""
        from rl_tpu.envs import PendulumEnv, RandomTruncationTransform, TransformedEnv, VmapEnv

        env = VmapEnv(
            TransformedEnv(PendulumEnv(), RandomTruncationTransform(p=0.5, seed=3)), 32
        )
        state, td = env.reset(KEY)
        td = td.set("action", jnp.zeros((32, 1)))
        _, out = env.step(state, td)
        t = np.asarray(out["next"]["truncated"])
        assert 0 < t.sum() < 32, t.sum()  # mixed, not all-or-nothing

    def test_unary_on_step_only_key(self):
        """reward exists only on the step path; reset must not crash."""
        from rl_tpu.envs import CartPoleEnv, TransformedEnv, UnaryTransform

        env = TransformedEnv(
            CartPoleEnv(), UnaryTransform("reward", "abs_r", jnp.abs)
        )
        state, td = env.reset(KEY)  # no KeyError
        assert "abs_r" not in td
        _, out = env.step(state, td.set("action", jnp.asarray(0)))
        assert float(out["next"]["abs_r"]) == 1.0
