"""Round-4 transform long tail (reference test/transforms/ strategy:
per-transform behavior in closed form + spec agreement via
check_env_specs + rollout structure)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import ArrayDict, Bounded, Composite, Unbounded
from rl_tpu.envs import (
    ConditionalPolicySwitch,
    ExpandAs,
    FlattenAction,
    MeanActionSelector,
    NextObservationDelta,
    NextStateReconstructor,
    RandomCropTensorDict,
    SuccessReward,
    TerminateTransform,
    TransformedEnv,
    check_env_specs,
    rollout,
)
from rl_tpu.envs.base import EnvBase
from rl_tpu.testing import ContinuousActionMock, CountingEnv

KEY = jax.random.key(0)


class MatrixActionEnv(EnvBase):
    """Mock with a (2, 3)-shaped box action; obs = row-sums of the action."""

    @property
    def observation_spec(self):
        return Composite(observation=Unbounded(shape=(2,)))

    @property
    def action_spec(self):
        return Bounded(shape=(2, 3), low=-1.0, high=1.0)

    def _reset(self, key):
        return ArrayDict(), ArrayDict(observation=jnp.zeros((2,)))

    def _step(self, state, action, key):
        assert action.shape[-2:] == (2, 3)  # env sees the ORIGINAL shape
        obs = ArrayDict(observation=action.sum(-1))
        false = jnp.asarray(False)
        return state, obs, jnp.asarray(0.0), false, false


class SuccessEnv(CountingEnv):
    """CountingEnv emitting a boolean success flag at count >= 3."""

    @property
    def observation_spec(self):
        from rl_tpu.data.specs import Binary

        return super().observation_spec.set("success", Binary(shape=()))

    def _reset(self, key):
        state, obs = super()._reset(key)
        return state, obs.set("success", jnp.asarray(False))

    def _step(self, state, action, key):
        state, obs, r, term, trunc = super()._step(state, action, key)
        return state, obs.set("success", obs["observation"][..., 0] >= 3), r, term, trunc


class TestFlattenAction:
    def test_spec_and_rollout(self):
        env = TransformedEnv(MatrixActionEnv(), FlattenAction(ndims=2))
        assert env.action_spec.shape == (6,)
        check_env_specs(env)

    def test_inv_restores_shape(self):
        env = TransformedEnv(MatrixActionEnv(), FlattenAction(ndims=2))
        state, td = env.reset(KEY)
        flat = jnp.arange(6, dtype=jnp.float32).reshape(6) / 6.0
        state, out = env.step(state, td.set("action", flat))
        # row sums of the unflattened (2,3) action
        expect = flat.reshape(2, 3).sum(-1)
        np.testing.assert_allclose(out["next", "observation"], expect, rtol=1e-6)

    def test_requires_env_attachment(self):
        t = FlattenAction(ndims=2)
        with pytest.raises(RuntimeError, match="TransformedEnv"):
            t.inv(ArrayDict(action=jnp.zeros((6,))))


class TestSuccessReward:
    def test_sparse_reward_and_spec(self):
        env = TransformedEnv(SuccessEnv(max_count=5), SuccessReward(scale=2.0))
        rspec = env.reward_spec
        assert float(rspec.high) == 2.0 and float(rspec.low) == 0.0
        check_env_specs(env)

    def test_reward_values(self):
        env = TransformedEnv(SuccessEnv(max_count=10), SuccessReward(scale=2.0))
        b = rollout(env, KEY, max_steps=6)
        success = np.asarray(b["next", "success"])
        reward = np.asarray(b["next", "reward"])
        np.testing.assert_allclose(reward, success.astype(np.float32) * 2.0)


class TestNextObservationDelta:
    def test_env_side_delta(self):
        env = TransformedEnv(CountingEnv(max_count=10), NextObservationDelta())
        check_env_specs(env)
        b = rollout(env, KEY, max_steps=5)
        delta = np.asarray(b["next", "delta", "observation"])
        assert delta.dtype == np.float16
        expect = np.asarray(b["next", "observation"]) - np.asarray(b["observation"])
        np.testing.assert_allclose(delta, expect.astype(np.float16))

    def test_rb_roundtrip_and_compact(self):
        nod = NextObservationDelta(in_keys=("observation",))
        obs = jnp.arange(6, dtype=jnp.float32).reshape(6, 1)
        nxt = obs + 0.5
        batch = ArrayDict(
            observation=obs,
            next=ArrayDict(
                observation=nxt,
                delta=ArrayDict(observation=(nxt - obs).astype(jnp.float16)),
            ),
        )
        compacted = nod.compact(batch)
        assert ("next", "observation") not in compacted
        rebuilt = nod(compacted)
        np.testing.assert_allclose(
            rebuilt["next", "observation"], nxt, atol=1e-3
        )
        assert ("next", "delta", "observation") not in rebuilt


class TestNextStateReconstructor:
    def test_shift_with_traj_and_done(self):
        obs = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        traj = jnp.asarray([0, 0, 0, 1, 1, 1, 2, 2])
        done = jnp.zeros(8, bool).at[1].set(True)  # traj 0 ends mid-batch
        batch = ArrayDict(
            observation=obs,
            collector=ArrayDict(traj_ids=traj),
            next=ArrayDict(done=done),
        )
        out = NextStateReconstructor()(batch)
        nxt = np.asarray(out["next", "observation"])[:, 0]
        # i=0: same traj, not done -> obs[1]; i=1: done -> NaN;
        # i=2: traj boundary -> NaN; i=6: same traj -> obs[7]; i=7: end -> NaN
        np.testing.assert_allclose(nxt[0], 1.0)
        assert np.isnan(nxt[1]) and np.isnan(nxt[2]) and np.isnan(nxt[5])
        np.testing.assert_allclose(nxt[3], 4.0)
        np.testing.assert_allclose(nxt[6], 7.0)
        assert np.isnan(nxt[7])

    def test_integer_key_requires_explicit_fill(self):
        batch = ArrayDict(
            tokens=jnp.arange(4, dtype=jnp.int32).reshape(4, 1),
            collector=ArrayDict(traj_ids=jnp.zeros(4, jnp.int32)),
            next=ArrayDict(done=jnp.zeros(4, bool)),
        )
        with pytest.raises(ValueError, match="integer"):
            NextStateReconstructor(keys=("tokens",))(batch)
        out = NextStateReconstructor(keys=("tokens",), fill_value=0)(batch)
        np.testing.assert_array_equal(
            np.asarray(out["next", "tokens"])[:, 0], [1, 2, 3, 0]
        )

    def test_strict_missing_marker_raises(self):
        batch = ArrayDict(observation=jnp.zeros((4, 1)))
        with pytest.raises(KeyError, match="traj_ids"):
            NextStateReconstructor()(batch)
        # non-strict: checks silently dropped, only the last row is NaN
        out = NextStateReconstructor(strict=False)(batch)
        assert np.isnan(np.asarray(out["next", "observation"])[-1]).all()

    def test_jit_safe(self):
        batch = ArrayDict(
            observation=jnp.arange(4, dtype=jnp.float32).reshape(4, 1),
            collector=ArrayDict(traj_ids=jnp.zeros(4, jnp.int32)),
            next=ArrayDict(done=jnp.zeros(4, bool)),
        )
        out = jax.jit(NextStateReconstructor())(batch)
        np.testing.assert_allclose(
            np.asarray(out["next", "observation"])[:3, 0], [1, 2, 3]
        )


class TestRandomCropTensorDict:
    def test_crop_shapes_and_contiguity(self):
        td = ArrayDict(
            x=jnp.broadcast_to(jnp.arange(10.0), (4, 10)),
            y=jnp.zeros((4, 10, 3)),
        )
        out = RandomCropTensorDict(sub_seq_len=4, seed=1)(td)
        assert out["x"].shape == (4, 4) and out["y"].shape == (4, 4, 3)
        x = np.asarray(out["x"])
        # each row is a contiguous arange slice
        np.testing.assert_allclose(np.diff(x, axis=1), 1.0)

    def test_mask_limits_crop(self):
        T, L = 10, 3
        lengths = np.array([4, 7, 10])
        mask = jnp.asarray(np.arange(T)[None, :] < lengths[:, None])
        td = ArrayDict(
            x=jnp.broadcast_to(jnp.arange(float(T)), (3, T)), mask=mask
        )
        out = RandomCropTensorDict(L, mask_key="mask", seed=2)(td)
        x = np.asarray(out["x"])
        for i, ln in enumerate(lengths):
            assert x[i].max() <= ln - 1  # crop stays in the valid prefix

    def test_too_short_raises(self):
        td = ArrayDict(x=jnp.zeros((2, 3)))
        with pytest.raises(RuntimeError, match="crop"):
            RandomCropTensorDict(5)(td)


class TestConditionalPolicySwitch:
    def test_opponent_keeps_count_even(self):
        # CountingEnv increments per step; the switch steps the opponent
        # whenever the post-step count is odd -> observed counts stay even
        switch = ConditionalPolicySwitch(
            policy=lambda td: td.set("action", jnp.asarray(0)),
            condition=lambda td: td["observation"][..., 0] % 2 == 1,
        )
        env = TransformedEnv(CountingEnv(max_count=100), switch)
        b = rollout(env, KEY, max_steps=6)
        counts = np.asarray(b["next", "observation"])[..., 0]
        assert (counts % 2 == 0).all(), counts

    def test_never_steps_past_episode_end(self):
        # max_count=3: termination fires at an ODD count, which also trips
        # the condition — the terminal transition must survive un-replaced
        switch = ConditionalPolicySwitch(
            policy=lambda td: td.set("action", jnp.asarray(0)),
            condition=lambda td: td["observation"][..., 0] % 2 == 1,
        )
        env = TransformedEnv(CountingEnv(max_count=3), switch)
        state, td = env.reset(KEY)
        for _ in range(2):
            state, out = env.step(state, env.rand_action(td, KEY))
            td = out["next"]
        assert float(td["observation"][0]) == 3.0  # terminal obs kept
        assert bool(td["terminated"]) and bool(td["done"])
        assert float(td["reward"]) == 1.0  # terminal reward kept

    def test_jit_rollout(self):
        switch = ConditionalPolicySwitch(
            policy=lambda td: td.set("action", jnp.asarray(0)),
            condition=lambda td: td["observation"][..., 0] % 2 == 1,
        )
        env = TransformedEnv(CountingEnv(max_count=100), switch)
        fn = jax.jit(
            lambda k: rollout(env, k, max_steps=4)
        )
        counts = np.asarray(fn(KEY)["next", "observation"])[..., 0]
        assert (counts % 2 == 0).all()


class TestMeanActionSelector:
    def test_belief_wrap_and_unwrap(self):
        env = TransformedEnv(ContinuousActionMock(), MeanActionSelector())
        state, td = env.reset(KEY)
        assert ("observation", "mean") in td and ("observation", "var") in td
        d = td["observation", "mean"].shape[-1]
        assert td["observation", "var"].shape[-2:] == (d, d)
        np.testing.assert_allclose(td["observation", "var"], 0.0)
        # policy writes (action, mean); env receives the flat action
        a = jnp.full((2,), 0.3)
        state, out = env.step(state, td.set(("action", "mean"), a))
        assert ("observation", "mean") in out["next"]

    def test_spec(self):
        env = TransformedEnv(ContinuousActionMock(), MeanActionSelector())
        spec = env.observation_spec
        assert ("observation", "mean") in spec
        assert spec["observation", "var"].shape == (4, 4)


class TestExpandAs:
    def test_expand_done_to_obs(self):
        env = TransformedEnv(
            ContinuousActionMock(),
            ExpandAs("done", "observation", out_key="done_wide"),
        )
        state, td = env.reset(KEY)
        assert td["done_wide"].shape == td["observation"].shape
        b = rollout(env, KEY, max_steps=3)
        dw = np.asarray(b["next", "done_wide"])
        dn = np.asarray(b["next", "done"])
        np.testing.assert_array_equal(dw, np.broadcast_to(dn[..., None], dw.shape))

    def test_spec(self):
        env = TransformedEnv(
            ContinuousActionMock(),
            ExpandAs("done", "observation", out_key="done_wide"),
        )
        assert env.done_spec["done_wide"].shape == (4,)


class TestTerminateTransform:
    def test_predicate_terminates(self):
        env = TransformedEnv(
            CountingEnv(max_count=100),
            TerminateTransform(lambda td: td["observation"][..., 0] >= 2),
        )
        b = rollout(env, KEY, max_steps=8)
        obs = np.asarray(b["next", "observation"])[..., 0]
        term = np.asarray(b["next", "terminated"])
        done = np.asarray(b["next", "done"])
        np.testing.assert_array_equal(term, obs >= 2)
        assert (done | ~term).all()  # done OR'ed in wherever terminated
        # auto-reset restarts after the predicate fires: counts stay <= 2
        assert obs.max() <= 2

    def test_write_done_false(self):
        env = TransformedEnv(
            CountingEnv(max_count=100),
            TerminateTransform(
                lambda td: td["observation"][..., 0] >= 2, write_done=False
            ),
        )
        state, td = env.reset(KEY)
        for _ in range(2):
            state, out = env.step(state, env.rand_action(td, KEY))
            td = out["next"]
        assert bool(td["terminated"]) and not bool(td["done"])


class TestMacroPrimitive:
    def test_move_interpolates_to_target(self):
        from rl_tpu.envs import MacroPrimitiveTransform, TargetMacroAction

        t = MacroPrimitiveTransform(macro_steps=4, settle_steps=2)
        macro = TargetMacroAction.move(jnp.asarray([1.0, -2.0]), steps=4)
        out = t.inv(ArrayDict(action=macro))
        seq = np.asarray(out["action"])
        assert seq.shape == (6, 2)
        np.testing.assert_allclose(seq[0], [0.25, -0.5])
        np.testing.assert_allclose(seq[3], [1.0, -2.0])
        np.testing.assert_allclose(seq[4:], [[1.0, -2.0]] * 2)  # settle holds

    def test_short_macro_holds_target(self):
        from rl_tpu.envs import MacroPrimitiveTransform, TargetMacroAction

        t = MacroPrimitiveTransform(macro_steps=4)
        macro = TargetMacroAction.move(jnp.asarray([2.0]), steps=2)
        seq = np.asarray(t.inv(ArrayDict(action=macro))["action"])
        np.testing.assert_allclose(seq[:, 0], [1.0, 2.0, 2.0, 2.0])

    def test_wait_holds_current(self):
        from rl_tpu.envs import MacroPrimitiveTransform, TargetMacroAction

        t = MacroPrimitiveTransform(macro_steps=3)
        macro = TargetMacroAction.wait(action_dim=2, steps=3)
        td = ArrayDict(action=macro, current_action=jnp.asarray([0.5, 0.5]))
        seq = np.asarray(t.inv(td)["action"])
        np.testing.assert_allclose(seq, [[0.5, 0.5]] * 3)

    def test_raw_tensor_is_move_target(self):
        from rl_tpu.envs import MacroPrimitiveTransform

        t = MacroPrimitiveTransform(macro_steps=2)
        seq = np.asarray(t.inv(ArrayDict(action=jnp.asarray([1.0])))["action"])
        np.testing.assert_allclose(seq[:, 0], [0.5, 1.0])

    def test_executes_through_multiaction_env(self):
        from rl_tpu.envs import MacroPrimitiveTransform, MultiActionEnv, TargetMacroAction, TransformedEnv
        from rl_tpu.testing import ContinuousActionMock

        T = 4
        env = TransformedEnv(
            MultiActionEnv(ContinuousActionMock(act_dim=2), T),
            MacroPrimitiveTransform(macro_steps=3, settle_steps=1, action_dim=2),
        )
        state, td = env.reset(KEY)
        macro = TargetMacroAction.move(jnp.asarray([0.5, -0.5]), steps=3)
        state, out = env.step(state, td.set("action", macro))
        # one outer step executed T low-level steps (reward accumulated)
        assert np.isfinite(float(out["next", "reward"]))


class TestActionTokenizerTransform:
    def test_rb_encode_decode(self):
        from rl_tpu.data import UniformActionTokenizer
        from rl_tpu.envs import ActionTokenizerTransform

        tok = UniformActionTokenizer(256, low=-1.0, high=1.0)
        t = ActionTokenizerTransform(tok)
        batch = ArrayDict(action=jnp.asarray([[0.5, -0.5]]))
        enc = t(batch)
        assert enc["action_tokens"].dtype == jnp.int32
        dec = ActionTokenizerTransform(tok, mode="decode")(enc.exclude("action"))
        np.testing.assert_allclose(
            np.asarray(dec["action"]), [[0.5, -0.5]], atol=1.0 / 255
        )

    def test_env_inv_decodes_policy_tokens(self):
        from rl_tpu.data import UniformActionTokenizer
        from rl_tpu.envs import ActionTokenizerTransform, TransformedEnv
        from rl_tpu.testing import ContinuousActionMock

        tok = UniformActionTokenizer(64, low=-1.0, high=1.0)
        env = TransformedEnv(
            ContinuousActionMock(act_dim=2), ActionTokenizerTransform(tok)
        )
        from rl_tpu.data import Categorical as CatSpec

        assert isinstance(env.action_spec, CatSpec)
        assert env.action_spec.n == 64
        state, td = env.reset(KEY)
        tokens = jnp.asarray([10, 50], jnp.int32)
        state, out = env.step(state, td.set("action", tokens))
        assert np.isfinite(np.asarray(out["next", "observation"])).all()

    def test_batched_structured_macros(self):
        from rl_tpu.envs import MacroPrimitiveTransform

        t = MacroPrimitiveTransform(macro_steps=4)
        macro = ArrayDict(
            mode=jnp.asarray([1, 0], jnp.int32),  # MOVE, WAIT
            steps=jnp.asarray([4, 2], jnp.int32),
            settle_steps=jnp.zeros((2,), jnp.int32),
            target=jnp.asarray([[1.0, -1.0], [9.0, 9.0]]),
        )
        seq = np.asarray(t.inv(ArrayDict(action=macro))["action"])
        assert seq.shape == (2, 4, 2)
        np.testing.assert_allclose(seq[0, 3], [1.0, -1.0])  # MOVE arrives
        np.testing.assert_allclose(seq[0, 0], [0.25, -0.25])
        np.testing.assert_allclose(seq[1], 0.0)  # WAIT holds zeros
