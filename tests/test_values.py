"""Value-estimator tests against brute-force references (strategy mirrors
reference test/objectives/test_values.py: every vectorized kernel checked
against a python-loop ground truth, with done/terminated distinction)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.ops.value import (
    generalized_advantage_estimate,
    linear_recurrence_reverse,
    reward2go,
    td0_return_estimate,
    td1_return_estimate,
    td_lambda_return_estimate,
    vtrace_advantage_estimate,
)

KEY = jax.random.key(42)


def make_data(T=20, B=3, seed=0, p_done=0.2):
    rng = np.random.default_rng(seed)
    reward = rng.normal(size=(T, B)).astype(np.float32)
    value = rng.normal(size=(T, B)).astype(np.float32)
    next_value = rng.normal(size=(T, B)).astype(np.float32)
    terminated = rng.random((T, B)) < p_done / 2
    truncated = rng.random((T, B)) < p_done / 2
    done = terminated | truncated
    return reward, value, next_value, done, terminated


def brute_gae(gamma, lmbda, value, next_value, reward, done, terminated):
    T, B = reward.shape
    adv = np.zeros_like(reward)
    for b in range(B):
        running = 0.0
        for t in reversed(range(T)):
            delta = reward[t, b] + gamma * next_value[t, b] * (1 - terminated[t, b]) - value[t, b]
            running = delta + gamma * lmbda * (1 - done[t, b]) * running
            adv[t, b] = running
    return adv, adv + value


def brute_td_lambda(gamma, lmbda, next_value, reward, done, terminated):
    T, B = reward.shape
    ret = np.zeros_like(reward)
    for b in range(B):
        nxt = None
        for t in reversed(range(T)):
            if t == T - 1 or done[t, b]:
                g = reward[t, b] + gamma * (1 - terminated[t, b]) * next_value[t, b]
            else:
                g = reward[t, b] + gamma * (1 - terminated[t, b]) * (
                    (1 - lmbda) * next_value[t, b] + lmbda * nxt
                )
            ret[t, b] = g
            nxt = g
    return ret


class TestLinearRecurrence:
    @pytest.mark.slow
    def test_matches_loop(self):
        a = jnp.asarray(np.random.default_rng(0).uniform(0, 1, (10, 2)), jnp.float32)
        b = jnp.asarray(np.random.default_rng(1).normal(size=(10, 2)), jnp.float32)
        y = np.asarray(linear_recurrence_reverse(a, b))
        expected = np.zeros_like(y)
        run = np.zeros(2)
        for t in reversed(range(10)):
            run = np.asarray(b)[t] + np.asarray(a)[t] * run
            expected[t] = run
        np.testing.assert_allclose(y, expected, rtol=1e-5)

    @pytest.mark.slow
    def test_gradients_flow(self):
        def f(b):
            return linear_recurrence_reverse(0.9 * jnp.ones_like(b), b).sum()

        g = jax.grad(f)(jnp.ones((5,)))
        # d sum(y)/d b_t = sum of discounts reaching b_t = (1-0.9^(t+1))/0.1
        np.testing.assert_allclose(
            np.asarray(g), [(1 - 0.9 ** (t + 1)) / 0.1 for t in range(5)], rtol=1e-5
        )


@pytest.mark.parametrize("gamma,lmbda", [(0.99, 0.95), (0.9, 1.0), (1.0, 0.5)])
class TestGAE:
    @pytest.mark.slow
    def test_matches_bruteforce(self, gamma, lmbda):
        reward, value, next_value, done, terminated = make_data()
        adv, target = generalized_advantage_estimate(
            gamma, lmbda, value, next_value, reward, done, terminated
        )
        badv, btarget = brute_gae(gamma, lmbda, value, next_value, reward, done, terminated)
        np.testing.assert_allclose(np.asarray(adv), badv, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(target), btarget, rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_jit_and_vmap_agree(self, gamma, lmbda):
        reward, value, next_value, done, terminated = make_data()
        f = jax.jit(
            lambda *xs: generalized_advantage_estimate(gamma, lmbda, *xs)
        )
        adv1, _ = f(value, next_value, reward, done, terminated)
        adv2, _ = generalized_advantage_estimate(
            gamma, lmbda, value, next_value, reward, done, terminated
        )
        np.testing.assert_allclose(np.asarray(adv1), np.asarray(adv2), rtol=1e-5, atol=1e-5)


class TestTD:
    def test_td0(self):
        reward, value, next_value, done, terminated = make_data()
        target = td0_return_estimate(0.99, next_value, reward, terminated)
        expected = reward + 0.99 * next_value * (1 - terminated)
        np.testing.assert_allclose(np.asarray(target), expected, rtol=1e-5)

    @pytest.mark.slow
    def test_td_lambda_matches_bruteforce(self):
        reward, value, next_value, done, terminated = make_data(T=15)
        target = td_lambda_return_estimate(0.95, 0.8, next_value, reward, done, terminated)
        expected = brute_td_lambda(0.95, 0.8, next_value, reward, done, terminated)
        np.testing.assert_allclose(np.asarray(target), expected, rtol=1e-4, atol=1e-5)

    def test_td1_is_lambda_one(self):
        reward, value, next_value, done, terminated = make_data(T=15)
        t1 = td1_return_estimate(0.95, next_value, reward, done, terminated)
        tl = td_lambda_return_estimate(0.95, 1.0, next_value, reward, done, terminated)
        np.testing.assert_allclose(np.asarray(t1), np.asarray(tl), rtol=1e-4, atol=1e-5)

    def test_td_lambda_zero_is_td0_without_cuts(self):
        reward, value, next_value, _, _ = make_data(p_done=0.0)
        zeros = np.zeros_like(reward, dtype=bool)
        tl = td_lambda_return_estimate(0.9, 0.0, next_value, reward, zeros, zeros)
        t0 = td0_return_estimate(0.9, next_value, reward, zeros)
        np.testing.assert_allclose(np.asarray(tl), np.asarray(t0), rtol=1e-5)


class TestVTrace:
    def test_on_policy_reduces_to_gae_lambda1(self):
        # with rho=c=1 (on-policy, no clip active) vtrace target == td1-style
        reward, value, next_value, done, terminated = make_data(p_done=0.0)
        log_rhos = jnp.zeros_like(reward)
        adv, vs = vtrace_advantage_estimate(
            0.99, log_rhos, value, next_value, reward, done, terminated
        )
        gadv, gtarget = generalized_advantage_estimate(
            0.99, 1.0, value, next_value, reward, done, terminated
        )
        np.testing.assert_allclose(np.asarray(vs), np.asarray(gtarget), rtol=1e-4, atol=1e-5)

    def test_clipping_reduces_weight(self):
        reward, value, next_value, done, terminated = make_data(p_done=0.0)
        big = 3.0 * jnp.ones_like(reward)  # rho = e^3 >> 1 -> clipped to 1
        adv_clip, _ = vtrace_advantage_estimate(
            0.99, big, value, next_value, reward, done, terminated, rho_clip=1.0
        )
        adv_on, _ = vtrace_advantage_estimate(
            0.99, jnp.zeros_like(reward), value, next_value, reward, done, terminated
        )
        np.testing.assert_allclose(np.asarray(adv_clip), np.asarray(adv_on), rtol=1e-4, atol=1e-5)


class TestReward2Go:
    @pytest.mark.slow
    def test_resets_at_done(self):
        reward = jnp.ones((6, 1))
        done = jnp.asarray([[0], [0], [1], [0], [0], [1]], bool)
        r2g = reward2go(reward, done, gamma=1.0)
        np.testing.assert_allclose(np.asarray(r2g).squeeze(-1), [3, 2, 1, 3, 2, 1])

    def test_discounting(self):
        reward = jnp.ones((3, 1))
        done = jnp.zeros((3, 1), bool)
        r2g = reward2go(reward, done, gamma=0.5)
        np.testing.assert_allclose(np.asarray(r2g).squeeze(-1), [1.75, 1.5, 1.0])
