"""VLA action tokenizers (round-3 VERDICT missing #7; reference
test/test_vla.py tokenizer round-trips + the tokenizers.py doctest
values)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import (
    ArrayDict,
    AddActionChunks,
    UniformActionTokenizer,
    VocabTailActionTokenizer,
    build_action_chunks,
)

KEY = jax.random.key(0)


class TestUniform:
    def test_reference_doctest_values(self):
        tok = UniformActionTokenizer(256, low=-1.0, high=1.0)
        np.testing.assert_array_equal(
            np.asarray(tok.encode(jnp.asarray([-1.0, 0.0, 1.0]))), [0, 128, 255]
        )
        np.testing.assert_allclose(
            np.asarray(tok.decode(jnp.asarray([0, 128, 255]))),
            [-0.998, 0.002, 0.998], atol=1e-2,
        )
        assert tok.vocab_size == 256

    def test_roundtrip_error_bound(self):
        tok = UniformActionTokenizer(128, low=-2.0, high=3.0)
        a = jax.random.uniform(KEY, (1000, 4), minval=-2.0, maxval=3.0)
        err = jnp.abs(tok.decode(tok.encode(a)) - a)
        assert float(err.max()) <= 5.0 / (2 * 128) + 1e-6  # half bin width

    def test_per_dim_bounds(self):
        tok = UniformActionTokenizer(
            64, low=jnp.asarray([-1.0, 0.0]), high=jnp.asarray([1.0, 10.0])
        )
        assert tok.action_dim == 2
        a = jnp.asarray([[0.0, 5.0]])
        assert float(jnp.abs(tok.decode(tok.encode(a)) - a).max()) < 0.1

    def test_chunk_shapes_jit(self):
        tok = UniformActionTokenizer(256, low=-1.0, high=1.0)
        chunks = jax.random.uniform(KEY, (2, 5, 8, 7), minval=-1, maxval=1)
        toks = jax.jit(tok.encode)(chunks)
        assert toks.shape == chunks.shape and toks.dtype == jnp.int32
        assert jax.jit(tok.decode)(toks).shape == chunks.shape

    def test_validation(self):
        with pytest.raises(ValueError, match="num_bins"):
            UniformActionTokenizer(0, low=-1.0, high=1.0)
        with pytest.raises(ValueError, match="strictly greater"):
            UniformActionTokenizer(8, low=1.0, high=1.0)


class TestVocabTail:
    def test_reference_doctest_values(self):
        tok = VocabTailActionTokenizer(256)
        np.testing.assert_array_equal(
            np.asarray(tok.encode(jnp.asarray([-1.0, 0.0, 1.0]))), [255, 128, 0]
        )
        np.testing.assert_allclose(
            np.asarray(tok.decode(jnp.asarray([255, 128, 0]))),
            [-0.9961, 0.0, 0.9961], atol=1e-4,
        )
        full = VocabTailActionTokenizer(256, full_vocab_size=32000)
        np.testing.assert_array_equal(
            np.asarray(full.encode(jnp.asarray([-1.0, 0.0, 1.0]))),
            [31999, 31872, 31744],
        )
        assert full.vocab_size == 32000

    def test_roundtrip_in_unit_box(self):
        tok = VocabTailActionTokenizer(256)
        a = jax.random.uniform(KEY, (500, 7), minval=-1, maxval=1)
        err = jnp.abs(tok.decode(tok.encode(a)) - a)
        assert float(err.max()) <= 2.0 / 255 + 1e-6

    def test_norm_stats_roundtrip(self):
        q01 = np.asarray([-0.3, -2.0, 0.0])
        q99 = np.asarray([0.3, 2.0, 1.0])
        tok = VocabTailActionTokenizer(256, norm_low=q01, norm_high=q99)
        a = jnp.asarray([[0.0, 1.5, 0.25], [-0.29, -1.9, 0.9]])
        dec = tok.decode(tok.encode(a))
        np.testing.assert_allclose(np.asarray(dec), np.asarray(a), atol=2e-2)

    def test_gripper_binarize_and_invert(self):
        q01, q99 = np.asarray([-1.0, -1.0]), np.asarray([1.0, 1.0])
        mask = np.asarray([True, False])  # dim 1 = gripper
        tok = VocabTailActionTokenizer(
            256, norm_low=q01, norm_high=q99, norm_mask=mask,
            gripper_binarize=True, gripper_invert=True,
        )
        a = jnp.asarray([[0.5, 0.7], [0.5, -0.7]])
        dec = np.asarray(tok.decode(tok.encode(a)))
        # gripper: binarized to +-1 then inverted
        np.testing.assert_allclose(dec[:, 1], [-1.0, 1.0])
        np.testing.assert_allclose(dec[:, 0], 0.5, atol=1e-2)

    def test_validation(self):
        with pytest.raises(ValueError, match="full_vocab_size"):
            VocabTailActionTokenizer(256, full_vocab_size=8)
        with pytest.raises(ValueError, match="together"):
            VocabTailActionTokenizer(256, norm_low=np.zeros(2))


class TestPolicyPath:
    def test_tokenized_chunks_through_schema(self):
        """The VLA pipeline: trajectory actions -> chunks -> tokens (the
        autoregressive policy's targets) -> decode -> env actions."""
        tok = UniformActionTokenizer(256, low=-1.0, high=1.0)
        actions = jax.random.uniform(KEY, (2, 6, 3), minval=-1, maxval=1)
        td = AddActionChunks(chunk=4)(ArrayDict(action=actions))
        chunks = td["vla_action", "chunk"]  # [2, 6, 4, 3]
        tokens = tok.encode(chunks)
        assert tokens.shape == (2, 6, 4, 3)
        # a token-head policy emits these ids; decode feeds the env
        env_actions = tok.decode(tokens)
        np.testing.assert_allclose(
            np.asarray(env_actions), np.asarray(chunks), atol=1.0 / 255
        )

    def test_lm_head_targets_in_vocab(self):
        tok = VocabTailActionTokenizer(64, full_vocab_size=1000)
        a = jax.random.uniform(KEY, (16, 8, 7), minval=-1, maxval=1)
        ids = np.asarray(tok.encode(a))
        assert ids.min() >= 1000 - 64 and ids.max() < 1000


class TestTinyVLA:
    def _td(self, B=2):
        from rl_tpu.modules import hash_instruction

        return ArrayDict(
            observation=ArrayDict(
                image=jnp.zeros((B, 16, 16, 3), jnp.uint8),
                state=jnp.zeros((B, 5)),
            ),
            language_instruction=hash_instruction(["pick", "place"][:B]),
        )

    def test_continuous_chunk_head(self):
        from rl_tpu.modules import TinyVLA

        policy = TinyVLA(action_dim=7, chunk_size=4)
        td = self._td()
        params = policy.init(KEY, td)
        out = jax.jit(policy)(params, td)
        assert out["vla_action", "chunk"].shape == (2, 4, 7)
        np.testing.assert_allclose(
            np.asarray(out["action"]), np.asarray(out["vla_action", "chunk"])[:, 0]
        )

    def test_language_conditioning(self):
        from rl_tpu.modules import TinyVLA, hash_instruction

        policy = TinyVLA(action_dim=3, chunk_size=2)
        td = self._td()
        params = policy.init(KEY, td)
        a1 = policy(params, td)["vla_action", "chunk"]
        td2 = td.set("language_instruction", hash_instruction(["open", "close"]))
        a2 = policy(params, td2)["vla_action", "chunk"]
        assert float(jnp.abs(a1 - a2).max()) > 1e-6  # instruction matters

    def test_token_head_with_tokenizer_roundtrip(self):
        from rl_tpu.modules import TinyVLA

        tok = UniformActionTokenizer(64, low=-1.0, high=1.0)
        policy = TinyVLA(
            action_dim=3, chunk_size=2, action_head="tokens",
            vocab_size=64, action_tokenizer=tok,
        )
        td = self._td()
        params = policy.init(KEY, td)
        out = jax.jit(lambda p, t, k: policy(p, t, k))(params, td, KEY)
        tokens = out["vla_action", "tokens"]
        assert tokens.shape == (2, 2, 3) and tokens.dtype == jnp.int32
        assert int(np.asarray(tokens).max()) < 64
        # decoded chunk is the tokenizer's decode of the emitted tokens
        np.testing.assert_allclose(
            np.asarray(out["vla_action", "chunk"]),
            np.asarray(tok.decode(tokens)),
        )
        # sequence log-prob is one scalar per sample
        assert out["vla_action", "log_probs"].shape == (2,)

    def test_deterministic_vs_sampled_tokens(self):
        from rl_tpu.modules import TinyVLA

        policy = TinyVLA(action_dim=2, chunk_size=2, action_head="tokens", vocab_size=16)
        td = self._td()
        params = policy.init(KEY, td)
        det1 = policy(params, td)["vla_action", "tokens"]
        det2 = policy(params, td)["vla_action", "tokens"]
        np.testing.assert_array_equal(np.asarray(det1), np.asarray(det2))
        # the SAMPLED path: reproducible per key, and across several keys
        # at least one draw departs from the argmax readout
        s1 = policy(params, td, jax.random.key(7))["vla_action", "tokens"]
        s2 = policy(params, td, jax.random.key(7))["vla_action", "tokens"]
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        differs = any(
            not np.array_equal(
                np.asarray(policy(params, td, jax.random.key(i))["vla_action", "tokens"]),
                np.asarray(det1),
            )
            for i in range(5)
        )
        assert differs
        # token head WITHOUT tokenizer: honest out_keys (no "action")
        out = policy(params, td, jax.random.key(0))
        assert ("action",) not in policy.out_keys
        assert ("vla_action", "chunk") not in out

    def test_token_log_probs_token_mode(self):
        from rl_tpu.modules import TinyVLA

        policy = TinyVLA(action_dim=2, chunk_size=3, action_head="tokens",
                         vocab_size=16, log_probs_mode="token")
        td = self._td()
        params = policy.init(KEY, td)
        out = policy(params, td, KEY)
        assert out["vla_action", "log_probs"].shape == (2, 3, 2)

    def test_validation(self):
        from rl_tpu.modules import TinyVLA

        with pytest.raises(ValueError, match="action_head"):
            TinyVLA(action_dim=2, chunk_size=2, action_head="nope")
        tok = UniformActionTokenizer(32, low=-1.0, high=1.0)
        with pytest.raises(ValueError, match="vocab"):
            TinyVLA(action_dim=2, chunk_size=2, action_head="tokens",
                    vocab_size=64, action_tokenizer=tok)


class TestToyVLAEnv:
    def test_echo_mode_schema_and_cadence(self):
        from rl_tpu.envs import ToyVLAEnv, check_env_specs, rollout
        from rl_tpu.modules import MultiStepActorWrapper

        env = ToyVLAEnv(action_dim=2, state_dim=4)
        check_env_specs(env)
        # a chunk policy's playout cadence is readable from next.state:
        # plan [0.1, 0.2, 0.3, 0.4] per dim, executed one step at a time
        plan = jnp.tile(jnp.asarray([[0.1], [0.2], [0.3], [0.4]]), (1, 2))
        wrap = MultiStepActorWrapper(
            lambda p, td, k: jnp.broadcast_to(plan, td["done"].shape + (4, 2)),
            n_steps=4, action_shape=(2,),
        )
        b = rollout(
            env, KEY, policy=lambda td, k: wrap(None, td, k), max_steps=4,
            policy_state=wrap.init_state(()),
        )
        echoed = np.asarray(b["next", "observation", "state"])[:, :2]
        np.testing.assert_allclose(echoed[:, 0], [0.1, 0.2, 0.3, 0.4], atol=1e-6)

    def test_tracking_oracle_succeeds_random_does_not(self):
        from rl_tpu.envs import ToyVLAEnv, rollout

        env = ToyVLAEnv(action_dim=2, state_dim=4, success_steps=3,
                        success_tol=0.2)

        def oracle(td, k):
            target = td["observation", "state"][..., 2:4]
            return td.set("action", target)

        b = rollout(env, KEY, policy=oracle, max_steps=6)
        assert bool(np.asarray(b["next", "success"]).any())
        assert bool(np.asarray(b["next", "terminated"]).any())
        # rewards are the negative tracking error: oracle gets ~0
        assert float(np.abs(np.asarray(b["next", "reward"])).max()) < 1e-5

        b_rand = rollout(env, jax.random.key(9), max_steps=6)
        assert not bool(np.asarray(b_rand["next", "success"]).any())

    def test_tinyvla_acts_in_env(self):
        from rl_tpu.envs import ToyVLAEnv, VmapEnv, rollout
        from rl_tpu.modules import TinyVLA

        env = VmapEnv(ToyVLAEnv(action_dim=2, state_dim=4), 3)
        policy = TinyVLA(action_dim=2, chunk_size=1, text_vocab=256)
        state, td = env.reset(KEY)
        params = policy.init(KEY, td)
        def act(td, k):
            out = policy(params, td, k)
            return out.set("action", jnp.clip(out["action"], -1, 1))

        b = rollout(env, KEY, policy=act, max_steps=3)
        assert b["next", "observation", "image"].shape == (3, 3, 16, 16, 3)
        assert np.isfinite(np.asarray(b["next", "reward"])).all()

    def test_validation(self):
        from rl_tpu.envs import ToyVLAEnv

        with pytest.raises(ValueError, match="state_dim"):
            ToyVLAEnv(action_dim=4, state_dim=6, success_steps=2)
