"""VLA schema, video-codec storage, services registry, render CLI tests
(reference analogs: test/test_vla.py schema validation, data/video.py decode
round-trips, services registry tests, render/cli tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_tpu.data import (
    AddActionChunks,
    ArrayDict,
    VideoCodecStorage,
    build_action_chunks,
    validate_vla_arraydict,
)


def _vla_td(B=2, T=5, A=3):
    return ArrayDict(
        observation=ArrayDict(
            image=ArrayDict(top=jnp.zeros((B, T, 8, 8, 3), jnp.uint8)),
            state=jnp.zeros((B, T, 4)),
        ),
        language_instruction=jnp.zeros((B, 6), jnp.int32),
        action=jnp.arange(B * T * A, dtype=jnp.float32).reshape(B, T, A),
    )


class TestVLASchema:
    def test_valid_passes(self):
        validate_vla_arraydict(_vla_td())

    def test_missing_action(self):
        td = _vla_td()
        td = ArrayDict({k: v for k, v in td.items() if k != "action"})
        with pytest.raises(ValueError, match="action"):
            validate_vla_arraydict(td)

    def test_bad_image_rank(self):
        td = _vla_td().set(("observation", "image", "top"), jnp.zeros((2, 5, 8), jnp.uint8))
        with pytest.raises(ValueError, match="image leaves"):
            validate_vla_arraydict(td)

    def test_chunks_required(self):
        with pytest.raises(ValueError, match="AddActionChunks"):
            validate_vla_arraydict(_vla_td(), require_chunks=True)

    def test_chunk_builder_values_and_padding(self):
        td = _vla_td(B=1, T=4, A=1)
        chunks, pad = build_action_chunks(td["action"], chunk=3)
        assert chunks.shape == (1, 4, 3, 1) and pad.shape == (1, 4, 3)
        a = np.asarray(td["action"])[0, :, 0]
        # step 0 sees actions [0,1,2]; step 3 sees [3,3,3] with pad True tail
        np.testing.assert_allclose(np.asarray(chunks)[0, 0, :, 0], a[:3])
        np.testing.assert_allclose(np.asarray(chunks)[0, 3, :, 0], [a[3]] * 3)
        assert not np.asarray(pad)[0, 0].any()
        assert np.asarray(pad)[0, 3].tolist() == [False, True, True]

    def test_transform_round_trip_validates(self):
        td = AddActionChunks(chunk=2)(_vla_td())
        validate_vla_arraydict(td, require_chunks=True)

    def test_chunk_builder_jits(self):
        td = _vla_td()
        f = jax.jit(lambda a: build_action_chunks(a, 3))
        chunks, pad = f(td["action"])
        assert chunks.shape == (2, 5, 3, 3)


class TestVideoCodecStorage:
    def _item(self, T=6, seed=0):
        rng = np.random.default_rng(seed)
        return ArrayDict(
            pixels=jnp.asarray(rng.integers(0, 255, (T, 16, 16, 3), np.uint8)),
            action=jnp.asarray(rng.normal(size=(T, 2)).astype(np.float32)),
        )

    def test_zlib_lossless_roundtrip(self):
        st = VideoCodecStorage(4, codec="zlib")
        state = st.init(None)
        item = self._item()
        state = st.set(state, [0], [item])
        out = st.get(state, [0])[0]
        np.testing.assert_array_equal(np.asarray(out["pixels"]), np.asarray(item["pixels"]))
        np.testing.assert_allclose(np.asarray(out["action"]), np.asarray(item["action"]))

    def test_auto_codec_roundtrip_and_compression(self):
        st = VideoCodecStorage(4, codec="auto")
        state = st.init(None)
        # smooth frames compress well under any codec
        T = 8
        base = np.zeros((T, 16, 16, 3), np.uint8)
        for t in range(T):
            base[t, :, : t + 2] = 100
        item = ArrayDict(pixels=jnp.asarray(base), action=jnp.zeros((T, 2)))
        state = st.set(state, [0], [item])
        out = st.get(state, [0])[0]
        assert out["pixels"].shape == (T, 16, 16, 3)
        if st.codec.name == "mp4":  # lossy: values close, not exact
            err = np.abs(
                np.asarray(out["pixels"], np.int32) - base.astype(np.int32)
            ).mean()
            assert err < 10, err
        else:
            np.testing.assert_array_equal(np.asarray(out["pixels"]), base)
        assert st.nbytes() < base.nbytes + 8 * 2 * 4

    def test_non_image_leaves_untouched(self):
        st = VideoCodecStorage(2, codec="zlib")
        state = st.init(None)
        item = self._item()
        state = st.set(state, [1], [item])
        out = st.get(state, [1])[0]
        assert out["action"].dtype == jnp.float32


class TestServicesRegistry:
    def test_in_process_registry(self):
        from rl_tpu.comm import ServiceRegistry

        reg = ServiceRegistry()
        reg.register("replay", {"host": "a", "port": 1})
        assert "replay" in reg and reg.get("replay")["port"] == 1
        with pytest.raises(ValueError):
            reg.register("replay", {})
        reg.register("replay", {"port": 2}, replace=True)
        assert reg.get("replay")["port"] == 2
        with pytest.raises(KeyError, match="unknown service"):
            reg.get("nope")

    def test_tcp_registry_with_watchdog(self):
        from rl_tpu.comm import TCPServiceRegistry, Watchdog, connect_registry

        wd = Watchdog(timeout=30)
        srv = TCPServiceRegistry(watchdog=wd)
        try:
            cli = connect_registry(*srv.address)
            cli.register("logger", {"host": "x", "port": 9})
            assert cli.get("logger") == {"host": "x", "port": 9}
            assert "logger" in cli.list()
            cli.heartbeat("logger")
            with pytest.raises(RuntimeError):
                cli.register("logger", {})  # duplicate -> remote error
        finally:
            srv.shutdown()

    def test_dead_service_lookup_fails(self):
        import time

        from rl_tpu.comm import ServiceRegistry, Watchdog

        wd = Watchdog(timeout=0.01)
        reg = ServiceRegistry(watchdog=wd)
        reg.register("flaky", {})
        time.sleep(0.03)
        wd.check()
        with pytest.raises(KeyError, match="not alive"):
            reg.get("flaky")
        reg.heartbeat("flaky")  # resurrect
        assert reg.get("flaky") == {}


class TestRenderCLI:
    def test_rasterizers_draw(self):
        from rl_tpu.render.frames import render_cartpole, render_pendulum

        f = render_cartpole(np.array([0.5, 0, 0.1, 0]))
        assert f.shape == (128, 192, 3) and (f < 255).any()
        f2 = render_pendulum(np.array([1.0, 0.0, 0.0]))
        assert f2.shape == (128, 128, 3) and (f2 < 255).any()

    def test_renderer_unwraps_transforms(self):
        from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
        from rl_tpu.render import renderer_for

        env = TransformedEnv(VmapEnv(CartPoleEnv(), 2), RewardSum())
        assert renderer_for(env) is not None

    def test_cli_gif_and_npz(self, tmp_path):
        from rl_tpu.render import main

        gif = str(tmp_path / "o.gif")
        assert main(["--env", "env/cartpole", "--steps", "8", "--out", gif]) == 0
        npz = str(tmp_path / "o.npz")
        assert main(["--env", "env/pendulum", "--steps", "5", "--out", npz]) == 0
        with np.load(npz) as z:
            assert any(k.startswith("next/") for k in z.files)


class TestReviewRegressions2:
    def test_batched_episode_len_pads_per_trajectory(self):
        # regression: [T,chunk] >= [B] broadcast crashed / mixed trajectories
        actions = jnp.zeros((2, 5, 1))
        _, pad = build_action_chunks(actions, chunk=2, episode_len=jnp.array([3, 5]))
        p = np.asarray(pad)
        assert p.shape == (2, 5, 2)
        assert p[0, 2].tolist() == [False, True]   # len-3 traj pads at t>=3
        assert not p[1, :4].any()                   # len-5 traj pads only at
        assert p[1, 4].tolist() == [False, True]    # the final chunk overhang

    def test_odd_dimension_frames_survive_codec(self):
        st = VideoCodecStorage(2, codec="auto")
        state = st.init(None)
        frames = np.zeros((4, 15, 17, 3), np.uint8)  # odd H/W
        frames[:, :7] = 200
        item = ArrayDict(pixels=jnp.asarray(frames))
        state = st.set(state, [0], [item])
        out = st.get(state, [0])[0]
        assert out["pixels"].shape == (4, 15, 17, 3)

    def test_pad_slots_hold_last_valid_action(self):
        # regression: gather must clamp at episode_len-1, not read past it
        actions = jnp.arange(10, dtype=jnp.float32).reshape(1, 10, 1)
        chunks, pad = build_action_chunks(actions, chunk=3, episode_len=jnp.array([4]))
        c = np.asarray(chunks)[0, :, :, 0]
        # step 3 (last valid): slots beyond the episode repeat action 3
        assert c[3].tolist() == [3.0, 3.0, 3.0]
        assert np.asarray(pad)[0, 3].tolist() == [False, True, True]
        # step 2 sees [2, 3, 3] — never action 4+ (the next packed episode)
        assert c[2].tolist() == [2.0, 3.0, 3.0]
