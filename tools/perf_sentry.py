"""Offline performance sentry: the committed-artifact regression gate.

Every healthy relay window commits measurement artifacts (``BENCH_*``
round captures, plus the distilled per-subsystem files: ``FLEET_pr6``,
``COMPILE_pr10``, ``PREFIX_pr11``, ``SPEC_pr16``, ``KERNELS_pr17``,
``PROF_pr18``, ...). Nothing *read* them back — a regression landed in
a commit looked identical to a win until a human diffed the JSON. This
tool closes that loop offline, the artifact-side complement of the
runtime :class:`~rl_tpu.obs.drift.DriftDetector`:

1. **Distill** every committed artifact into one schema-tolerant time
   series (whole-file JSON or JSONL; missing files, dead-relay rounds
   with ``parsed: null``, and pre-PR checkouts all tolerated — an absent
   series is *skipped*, never failed, so the gate works at every point
   in history).
2. **Enforce** the declared gate table below: headline throughput
   ratios, accepted-tokens/dispatch, cache hit rates, lost==0
   accounting, steady-state ``CompileDelta == 0``, and the PR-18
   armed-profiler overhead bound.
3. **Write** the roll-up to ``PERF_HISTORY.json`` (committed alongside
   the artifacts it summarizes) and exit nonzero iff any gate failed —
   the CI/watch-loop contract.

Usage::

    python tools/perf_sentry.py [--dir REPO] [--out PERF_HISTORY.json]
"""

from __future__ import annotations

import argparse
import datetime
import glob
import json
import os
import sys
from typing import Any, NamedTuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

__all__ = ["GATES", "Gate", "check", "load_records", "main"]


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


# -- schema-tolerant readers ---------------------------------------------------


def load_records(path: str) -> list[dict]:
    """Read one artifact into a list of dict records. Tolerates the two
    on-disk shapes (a single JSON object, or a JSONL stream like
    ``BENCH_pr2.json``) and skips unparseable lines instead of raising —
    the sentry must keep gating the healthy series even when one round's
    capture was cut off mid-write."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return []
    try:
        d = json.loads(raw)
        return [d] if isinstance(d, dict) else []
    except ValueError:
        pass
    out: list[dict] = []
    for ln in raw.splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if isinstance(d, dict):
            out.append(d)
    return out


def _lookup(d: Any, dotted: str) -> Any:
    """Nested dict lookup by dotted path; None when any hop is absent."""
    cur = d
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


# -- the gate table ------------------------------------------------------------


class Gate(NamedTuple):
    file: str  # artifact filename in --dir
    key: str  # dotted path inside the artifact
    op: str  # >=, >, <, <=, ==
    bound: float
    why: str  # what a failure means, for the report line


_OPS = {
    ">=": lambda v, b: v >= b,
    ">": lambda v, b: v > b,
    "<": lambda v, b: v < b,
    "<=": lambda v, b: v <= b,
    "==": lambda v, b: v == b,
}

# Bounds sit well below the committed values (e.g. spec_speedup_x
# measured 2.36, gated at 1.3) — the sentry is a regression floor, not a
# flakiness amplifier. Every ==0 gate is an invariant, not a margin.
GATES: list[Gate] = [
    Gate("FLEET_pr6.json", "lost", "==", 0,
         "chaos fleet lost an admitted request across the crash"),
    Gate("FLEET_pr6.json", "fleet_tokens_per_sec", ">", 0.0,
         "fleet produced no tokens"),
    Gate("FLEET_pr6.json", "steady_state_compile_delta", "==", 0,
         "the chaos window recompiled mid-traffic"),
    Gate("COMPILE_pr10.json", "compile.metrics.warm_speedup", ">=", 2.0,
         "warm start no longer beats cold start 2x"),
    Gate("COMPILE_pr10.json", "compile.metrics.steady_state_compile_delta",
         "==", 0, "warmed process still compiled in steady state"),
    Gate("PREFIX_pr11.json", "prefix.kv_prefix_hit_rate", ">=", 0.5,
         "prefix-KV hit rate collapsed on the shared-prefix workload"),
    Gate("PREFIX_pr11.json", "prefix.prefill_reduction_x", ">=", 2.0,
         "prefix reuse no longer halves prefill compute"),
    Gate("PREFIX_pr11.json", "prefix.lost", "==", 0,
         "prefix bench lost an admitted request under kvmem.evict"),
    Gate("PREFIX_pr11.json", "prefix.steady_state_compile_delta", "==", 0,
         "prefix traffic recompiled in steady state"),
    Gate("SPEC_pr16.json", "spec.spec_speedup_x", ">=", 1.3,
         "speculative decoding no longer beats the spec-off arm"),
    Gate("SPEC_pr16.json", "spec.accepted_tokens_per_dispatch", ">", 1.0,
         "draft acceptance fell below one token per verify dispatch"),
    Gate("SPEC_pr16.json", "spec.lost", "==", 0,
         "spec bench lost an admitted request under engine_crash"),
    Gate("SPEC_pr16.json", "spec.steady_state_compile_delta_spec", "==", 0,
         "the spec arm recompiled in steady state"),
    Gate("KERNELS_pr17.json", "kernels.int8_capacity_ratio_x", ">=", 1.5,
         "int8 KV no longer buys its capacity multiplier"),
    Gate("KERNELS_pr17.json", "kernels.steady_state_compile_delta_kernel",
         "==", 0, "the kernel arm recompiled in steady state"),
    Gate("PROF_pr18.json", "profiling.armed_overhead_frac", "<", 0.02,
         "the armed profiler/drift feed costs more than 2% of wall"),
    # PR-19 elasticity: the committed run measured burst attainment
    # 0.53 (autoscale) vs 0.22 (fixed), vs_baseline 2.46 — floors sit
    # well under that; the ==0 gates are invariants.
    Gate("AUTOSCALE_pr19.json", "autoscale.lost", "==", 0,
         "the elastic fleet lost a request across scale-up/down/crash"),
    Gate("AUTOSCALE_pr19.json", "autoscale.scale_up_compile_delta_max",
         "==", 0,
         "a scale-up warm compiled instead of loading from the store"),
    Gate("AUTOSCALE_pr19.json", "autoscale.steady_state_compile_delta",
         "==", 0, "the autoscale arm recompiled mid-traffic"),
    Gate("AUTOSCALE_pr19.json", "autoscale.value", ">=", 0.3,
         "burst-window SLO attainment under autoscaling collapsed"),
    Gate("AUTOSCALE_pr19.json", "autoscale.vs_baseline", ">=", 1.2,
         "the elastic arm no longer beats the fixed fleet through the burst"),
    Gate("AUTOSCALE_pr19.json", "autoscale.rollout_tokens_per_sec", ">", 0.0,
         "the batch-lane tenant harvested nothing from fleet slack"),
    Gate("AUTOSCALE_pr19.json", "autoscale.waste_frac", "<=", 0.65,
         "idle-capacity waste under autoscaling exceeded its ceiling"),
    Gate("AUTOSCALE_pr19.json", "autoscale.scale_ups", ">=", 1,
         "no scale-up fired on the seeded burst"),
    Gate("AUTOSCALE_pr19.json", "autoscale.scale_downs", ">=", 1,
         "no scale-down drained the post-burst slack"),
    # PR-20 sharded experience tier (REPLAY_pr20.json). Measured on the
    # cpu tier: 2.81x aggregate extend throughput over one endpoint at
    # the same total capacity (the PER write program carries O(capacity)
    # full-array work per extend, so N shards at C/N each pay 1/N of
    # it), chaos recovery 0.91s. Floors sit under those; the chaos
    # gates are invariants of the acceptance scenario.
    Gate("REPLAY_pr20.json", "replay_shard.shard_speedup_x", ">=", 2.0,
         "N shards no longer beat one endpoint by the 2x acceptance bound"),
    Gate("REPLAY_pr20.json", "replay_shard.value", ">", 0.0,
         "the sharded tier wrote nothing during the timed window"),
    Gate("REPLAY_pr20.json", "replay_shard.chaos.faults_fired", ">=", 1,
         "the seeded shard crash never fired — the chaos phase ran empty"),
    Gate("REPLAY_pr20.json", "replay_shard.chaos.learner_errors", "==", 0,
         "a shard crash leaked through the mixture to the learner"),
    Gate("REPLAY_pr20.json", "replay_shard.chaos.readmitted", ">=", 1,
         "the supervisor never re-admitted the crashed shard"),
    Gate("REPLAY_pr20.json", "replay_shard.chaos.recovery_s", "<=", 10.0,
         "crash-to-readmit exceeded the degradation budget"),
]


# -- distillation --------------------------------------------------------------


def _headline_series(dir: str) -> dict:
    """All ``{"metric": ..., "value": ...}`` headline records across the
    committed ``BENCH_*`` captures, keyed by metric name — the long-run
    time series a human (or a future trend gate) reads."""
    series: dict[str, list[dict]] = {}

    def _add(src: str, rec: dict) -> None:
        m, v = rec.get("metric"), rec.get("value")
        if not isinstance(m, str) or not isinstance(v, (int, float)):
            return
        series.setdefault(m, []).append({
            "source": src,
            "value": v,
            "unit": rec.get("unit"),
            "vs_baseline": rec.get("vs_baseline"),
        })

    for path in sorted(glob.glob(os.path.join(dir, "BENCH_*.json"))):
        src = os.path.basename(path)
        for rec in load_records(path):
            _add(src, rec)
            # round captures wrap the result: {"n": .., "parsed": {...}}
            parsed = rec.get("parsed")
            if isinstance(parsed, dict):
                _add(src, parsed)
            # aggregate lines nest sub-results under their mode names
            # ("parsed" was already taken above)
            for k, v in rec.items():
                if k != "parsed" and isinstance(v, dict):
                    _add(src, v)
    return series


def check(dir: str) -> tuple[list[dict], dict]:
    """Evaluate every gate against the artifacts in ``dir``. Returns
    (results, history): per-gate dicts with status pass/fail/skip, and
    the full PERF_HISTORY payload."""
    results: list[dict] = []
    for g in GATES:
        path = os.path.join(dir, g.file)
        recs = load_records(path)
        rec = recs[0] if recs else None
        value = _lookup(rec, g.key) if rec is not None else None
        if value is None or not isinstance(value, (int, float)):
            status = "skip"  # pre-PR checkout or never-captured artifact
        elif _OPS[g.op](value, g.bound):
            status = "pass"
        else:
            status = "fail"
        results.append({
            "file": g.file,
            "key": g.key,
            "op": g.op,
            "bound": g.bound,
            "value": value,
            "status": status,
            "why": g.why,
        })
    history = {
        "generated": _utcnow(),
        "gates": results,
        "gate_counts": {
            s: sum(1 for r in results if r["status"] == s)
            for s in ("pass", "fail", "skip")
        },
        "headline_series": _headline_series(dir),
    }
    return results, history


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO,
                    help="artifact directory (default: repo root)")
    ap.add_argument("--out", default=None,
                    help="history roll-up path (default <dir>/PERF_HISTORY.json)")
    args = ap.parse_args(argv)

    results, history = check(args.dir)
    out = args.out or os.path.join(args.dir, "PERF_HISTORY.json")
    with open(out, "w") as f:
        json.dump(history, f, indent=2, sort_keys=True)
        f.write("\n")

    failed = [r for r in results if r["status"] == "fail"]
    for r in results:
        mark = {"pass": "ok  ", "fail": "FAIL", "skip": "skip"}[r["status"]]
        print(f"{mark} {r['file']}:{r['key']} {r['op']} {r['bound']}"
              f" (value={r['value']})")
        if r["status"] == "fail":
            print(f"     -> {r['why']}")
    print(f"perf_sentry: {history['gate_counts']['pass']} pass, "
          f"{len(failed)} fail, {history['gate_counts']['skip']} skip "
          f"-> {os.path.relpath(out, args.dir)}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
