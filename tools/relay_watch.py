"""Relay watcher: poll the TPU relay, then SPEND the first healthy window.

Round-5 ran a watcher that only logged probe outcomes
(``logs/relay_watch_r05.log`` — 30+ hours of ``dead rc=124 (120s)`` lines,
and nobody was awake for the minutes the relay came back). This version
closes the loop: the first healthy ``BENCH_MODE=probe`` immediately launches
``BENCH_MODE=all``, writes the stdout JSONL to ``logs/``, and commits the
artifact, so a transient chip window always yields a committed measurement.

Usage::

    python tools/relay_watch.py --interval 720 --bench-timeout 900

Probe/bench/commit all go through a ``Runner`` object so tests can inject a
fake and exercise the state machine without subprocesses or a TPU.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

__all__ = ["Runner", "watch"]


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _parse_last_json(text: str) -> dict | None:
    for ln in reversed((text or "").strip().splitlines()):
        try:
            return json.loads(ln)
        except ValueError:
            continue
    return None


def _extract_metrics(stdout: str) -> dict:
    """Collect every ``"metrics"`` section from a bench stdout JSONL stream,
    keyed by sub-bench name (PR-3: device-metrics drains and observability
    overhead ride the bench artifact as structured data, not log grep).

    The rlhf sub-bench's ``pipeline`` sub-result (overlapped-cycle
    throughput, overlap_frac, staleness bound) is distilled the same way —
    it lands under the sub-bench's key as a ``pipeline`` entry, like the
    PER/async_collect timing splits. The fleet sub-bench (ISSUE-6 chaos
    traffic: pre/post-crash p50/p99 TTFT, tokens/s, shed/re-dispatched/
    lost accounting and its ``invariant_ok``) needs no special-casing —
    its ``metrics`` section rides through here like every other mode's."""

    def _section(v: dict) -> dict:
        sec: dict = {}
        if isinstance(v.get("metrics"), dict):
            sec.update(v["metrics"])
        if isinstance(v.get("pipeline"), dict):
            sec["pipeline"] = v["pipeline"]
        return sec

    sections: dict = {}
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        for k, v in d.items():
            # lines are either {"<name>": {...result...}} wrappers or the
            # final aggregate with sub-results nested under their names
            if isinstance(v, dict):
                sec = _section(v)
                if sec:
                    sections[k] = {**sections.get(k, {}), **sec}
        sec = _section(d)
        if sec:
            # a bare single-mode result line: key by its headline metric
            sections.setdefault(str(d.get("metric", "headline")), sec)
    return sections


def _extract_multichip(stdout: str) -> dict | None:
    """Find the multichip sub-bench result (the scaling-efficiency sweep:
    train MFU + tokens/s at 1/4/8 devices, sharded-vs-replicated ratio) in
    a bench stdout JSONL stream. Unlike the flat ``metrics`` sections, the
    sweep carries structure worth keeping whole — per-device-count worker
    dicts — so it lands in its own committed MULTICHIP artifact. Last
    match wins (the final aggregate line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        v = d.get("multichip")
        if isinstance(v, dict) and ("devices" in v or "scaling_efficiency" in v):
            found = v
    return found


def _extract_anakin(stdout: str) -> dict | None:
    """Find the anakin sub-bench result (ISSUE-9 fused env+policy+learner:
    env-steps/s/chip across the num_envs x device-count sweep, MFU per
    point, fused-vs-host-Collector ratio) in a bench stdout JSONL stream.
    Like the multichip sweep, the per-device worker dicts carry structure
    worth keeping whole, so they get their own committed ANAKIN artifact.
    Last match wins (the final aggregate line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        v = d.get("anakin")
        if isinstance(v, dict) and ("devices" in v or "num_envs_scaling" in v):
            found = v
    return found


def _extract_compile(stdout: str) -> dict | None:
    """Find the compile sub-bench result (ISSUE-10 cold-start kill: cold vs
    warm startup wall-clock over a shared executable store, per-program
    warmup sources, and the steady-state compile-delta assertion) in a
    bench stdout JSONL stream. The cold/warm role splits and per-program
    source counts carry structure worth keeping whole, so they get their
    own committed COMPILE artifact. Last match wins (the final aggregate
    line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        v = d.get("compile")
        if isinstance(v, dict) and ("warm_s" in v or "cold_s" in v):
            found = v
    return found


def _extract_prefix(stdout: str) -> dict | None:
    """Find the prefix sub-bench result (ISSUE-11 prefix-aware KV tier:
    measured prefill-compute reduction vs the legacy allocator, KV blocks
    charged per request, hit rate / CoW / eviction counters, and the
    lost==0 accounting under the mid-run ``kvmem.evict`` crash) in a
    bench stdout JSONL stream. The per-arm dicts (baseline vs prefix
    TTFT tails and token totals) carry structure worth keeping whole, so
    they get their own committed PREFIX artifact. Last match wins (the
    final aggregate line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        v = d.get("prefix")
        if isinstance(v, dict) and (
            "prefill_reduction_x" in v or "kv_prefix_hit_rate" in v
        ):
            found = v
    return found


def _extract_spec(stdout: str) -> dict | None:
    """Find the spec sub-bench result (ISSUE-16 speculative decoding:
    measured tokens/s speedup vs the spec-off arm on the replayed
    shared-prefix workload, accepted tokens per verify dispatch, draft
    hit rate, both arms' steady-state compile deltas, and the lost==0
    accounting under the mid-run ``fleet.engine_crash`` fault) in a
    bench stdout JSONL stream. The per-arm dicts (TTFT/latency tails and
    token totals) carry structure worth keeping whole, so they get their
    own committed SPEC artifact. Last match wins (the final aggregate
    line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        v = d.get("spec")
        if isinstance(v, dict) and (
            "spec_speedup_x" in v or "accepted_tokens_per_dispatch" in v
        ):
            found = v
    return found


def _extract_kernels(stdout: str) -> dict | None:
    """Find the kernels sub-bench result (ISSUE-17 Pallas kernel tier:
    per-kernel vs stock-XLA-fallback A/B on the seeded fleet replay plan
    — tokens/s both arms, per-dispatch decode device time, both arms'
    steady-state compile deltas, the PER sum-tree cycle rates + bit-
    parity, and the int8-KV capacity multiplier/accuracy delta) in a
    bench stdout JSONL stream. The per-arm dicts and the per-kernel
    ir_audit rows carry structure worth keeping whole, so they get their
    own committed KERNELS artifact. Last match wins (the final aggregate
    line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        for c in [d] + [v for v in d.values() if isinstance(v, dict)]:
            v = c.get("kernels")
            if isinstance(v, dict) and (
                "kernel_speedup_x" in v or "int8_capacity_ratio_x" in v
            ):
                found = v
    return found


def _extract_obs(stdout: str) -> dict | None:
    """Find the fleet sub-bench's ``obs`` section (PR-12 observability:
    trace-tree shape of the chaos traffic — span count, tree count, max
    parent-link depth, distinct threads — plus the SLO engine's windowed
    attainment/burn-rate snapshot and the flight-record bundle size cut
    from the run) in a bench stdout JSONL stream. Unlike the flat
    ``metrics`` sections, the per-objective SLO dicts carry structure
    worth keeping whole, so it lands in its own committed OBS artifact.
    Last match wins (the final aggregate line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        # lines are either {"<name>": {...result...}} wrappers or the
        # final aggregate with sub-results nested under their names
        for c in [d] + [v for v in d.values() if isinstance(v, dict)]:
            v = c.get("obs")
            if isinstance(v, dict) and ("trace_depth" in v or "slo" in v):
                found = v
    return found


def _extract_profiling(stdout: str) -> dict | None:
    """Find the fleet sub-bench's ``profiling`` section (PR-18 adaptive
    profiling: the armed TriggeredProfiler/DriftDetector's view of the
    chaos window — measured armed-feed overhead fraction vs the 2%
    bound, capture counts per trigger, suppressions, and the drift
    detector's per-program comparison roll-up) in a bench stdout JSONL
    stream. The per-trigger dicts carry structure worth keeping whole,
    so they get their own committed PROF artifact — which is also what
    the offline perf sentry gates. Last match wins (the final aggregate
    line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        for c in [d] + [v for v in d.values() if isinstance(v, dict)]:
            v = c.get("profiling")
            if isinstance(v, dict) and (
                "armed_overhead_frac" in v or "drift" in v
            ):
                found = v
    return found


def _extract_autoscale(stdout: str) -> dict | None:
    """Find the autoscale sub-bench result (ISSUE-19 elastic fleet: the
    seeded diurnal+burst replay run through a fixed-fleet arm and an
    SLO-burn-autoscaled arm — burst-window attainment both arms, the
    scale-up CompileDelta invariant, rollout batch-lane tokens/s from
    slack, idle-capacity waste, the scale event trail, and the
    prefill/decode handoff sub-result) in a bench stdout JSONL stream.
    The per-arm dicts and the autoscaler decision snapshot carry
    structure worth keeping whole, so they get their own committed
    AUTOSCALE artifact — which is also what the offline perf sentry
    gates. Last match wins (the final aggregate line repeats the
    sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        for c in [d] + [v for v in d.values() if isinstance(v, dict)]:
            v = c.get("autoscale")
            if isinstance(v, dict) and (
                "scale_up_compile_delta_max" in v
                or "rollout_tokens_per_sec" in v
            ):
                found = v
    return found


def _extract_replay(stdout: str) -> dict | None:
    """Find the replay_shard sub-bench result (ISSUE-20 sharded
    experience tier: the N-shard-vs-1-endpoint A/B — aggregate extend
    throughput both arms, end-to-end sample latency percentiles, and
    the seeded shard-crash chaos replay with learner-visible error
    count, re-admission flag, and crash-to-readmit seconds) in a bench
    stdout JSONL stream. The arm and chaos sub-dicts carry structure
    worth keeping whole, so they get their own committed REPLAY
    artifact — which is also what the offline perf sentry gates. Last
    match wins (the final aggregate line repeats the sub-results)."""
    found = None
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        for c in [d] + [v for v in d.values() if isinstance(v, dict)]:
            v = c.get("replay_shard")
            if isinstance(v, dict) and (
                "shard_speedup_x" in v
                or v.get("metric") == "replay_shard_extend_items_per_sec"
            ):
                found = v
    return found


def _extract_ir_audit(stdout: str) -> dict:
    """Collect every ``ir_audit`` section (PR-15 deep-tier auditor: per-
    program predicted-vs-measured MFU from the static roofline, audit
    findings count — 0, or the gate would have failed) from a bench
    stdout JSONL stream, keyed by sub-bench name. Structure-preserving
    like the multichip/obs extractors: per-program dicts go whole into
    the committed AUDIT artifact. Last match per sub-bench wins (the
    final aggregate line repeats the sub-results)."""
    found: dict = {}
    for ln in (stdout or "").strip().splitlines():
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict):
            continue
        for k, v in d.items():
            if isinstance(v, dict) and isinstance(v.get("ir_audit"), dict):
                found[k] = v["ir_audit"]
        if isinstance(d.get("ir_audit"), dict):
            found.setdefault(str(d.get("metric", "headline")), d["ir_audit"])
    return found


class Runner:
    """Real subprocess/git backend. Tests replace this with a fake that
    implements the same three methods."""

    def probe(self, timeout: float) -> tuple[int, str, float]:
        """Run BENCH_MODE=probe under a hard kill; (rc, stdout, seconds).
        rc=124 on timeout, matching the ``timeout(1)`` convention the round-5
        log used."""
        env = dict(os.environ, BENCH_MODE="probe")
        t0 = time.monotonic()
        try:
            p = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                env=env, capture_output=True, text=True, timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return 124, "", time.monotonic() - t0
        return p.returncode, p.stdout, time.monotonic() - t0

    def bench_all(self, timeout: float) -> tuple[int, str]:
        env = dict(os.environ, BENCH_MODE="all", BENCH_TIMEOUT=str(int(timeout)))
        try:
            p = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                env=env, capture_output=True, text=True, timeout=timeout + 120,
            )
        except subprocess.TimeoutExpired as e:
            out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
            return 124, out
        return p.returncode, p.stdout

    def rlint(self, artifact: str, timeout: float = 600.0) -> tuple[int, str]:
        """Refresh the rlint summary artifact (PR-8, deep tier PR-15):
        re-run the AST rules over rl_tpu/ AND compile the IR audit set
        (``--ir``) so the artifact records findings by rule across both
        tiers plus the per-program audit roll-up; ``--strict`` keeps the
        committed baseline free of stale suppressions. rc!=0 means
        unsuppressed findings (or a dead audit-set builder) — the
        artifact is still written so the regression is visible in-tree."""
        try:
            p = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, "tools", "rlint.py"),
                    "rl_tpu/",
                    "--ir",
                    "--strict",
                    "--artifact",
                    artifact,
                ],
                cwd=REPO, capture_output=True, text=True, timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return 124, ""
        return p.returncode, p.stdout

    def sentry(self, out: str, timeout: float = 120.0) -> tuple[int, str]:
        """Run the offline perf sentry (PR-18) over the repo's committed
        artifact series, refreshing ``PERF_HISTORY.json``. rc!=0 means a
        declared regression gate failed; the roll-up is still written so
        the regression is visible in-tree next to the artifact that
        introduced it."""
        try:
            p = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, "tools", "perf_sentry.py"),
                    "--out",
                    out,
                ],
                cwd=REPO, capture_output=True, text=True, timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return 124, ""
        return p.returncode, p.stdout

    def commit(self, paths: list[str], message: str) -> int:
        rc = subprocess.run(["git", "-C", REPO, "add", *paths]).returncode
        if rc != 0:
            return rc
        return subprocess.run(["git", "-C", REPO, "commit", "-m", message]).returncode


def watch(
    runner,
    log,
    interval: float = 720.0,
    probe_timeout: float = 120.0,
    bench_timeout: float = 900.0,
    max_probes: int | None = None,
    artifact: str | None = None,
    metrics_artifact: str | None = None,
    multichip_artifact: str | None = None,
    anakin_artifact: str | None = None,
    compile_artifact: str | None = None,
    prefix_artifact: str | None = None,
    spec_artifact: str | None = None,
    kernels_artifact: str | None = None,
    obs_artifact: str | None = None,
    audit_artifact: str | None = None,
    profiling_artifact: str | None = None,
    autoscale_artifact: str | None = None,
    replay_artifact: str | None = None,
    sentry_artifact: str | None = None,
    rlint_artifact: str | None = None,
    commit: bool = True,
    require_tpu: bool = True,
    sleep=time.sleep,
) -> str | None:
    """Probe until healthy, then run BENCH_MODE=all once, write + commit the
    artifact, and return its path (None if the probe budget ran out).

    ``log`` is a callable taking one formatted line; lines keep the round-5
    watcher's grammar (``<iso8601>Z dead rc=<rc> (<sec>s)``) so existing log
    tooling keeps parsing.
    """
    log(f"{_utcnow()} watcher start")
    n = 0
    while max_probes is None or n < max_probes:
        n += 1
        rc, out, dt = runner.probe(probe_timeout)
        info = _parse_last_json(out)
        healthy = (
            rc == 0
            and info is not None
            and info.get("error") is None
            and (not require_tpu or info.get("platform", "cpu") != "cpu")
        )
        if not healthy:
            log(f"{_utcnow()} dead rc={rc} ({dt:.0f}s)")
            sleep(interval)
            continue
        log(
            f"{_utcnow()} healthy platform={info.get('platform')} "
            f"kind={info.get('device_kind')} ({dt:.0f}s)"
        )
        brc, bout = runner.bench_all(bench_timeout)
        path = artifact or os.path.join(
            REPO, "logs", f"bench_{time.strftime('%Y%m%d_%H%M%S')}.jsonl"
        )
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(bout or "")
        log(f"{_utcnow()} bench rc={brc} artifact={os.path.relpath(path, REPO)}")
        paths = [path]
        sections = _extract_metrics(bout)
        if sections:
            mpath = metrics_artifact or os.path.join(REPO, "METRICS_pr3.json")
            with open(mpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "bench_metrics": sections,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(mpath)
            log(f"{_utcnow()} metrics -> {os.path.relpath(mpath, REPO)}")
        mc = _extract_multichip(bout)
        if mc is not None:
            mcpath = multichip_artifact or os.path.join(REPO, "MULTICHIP_r06.json")
            with open(mcpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "multichip": mc,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(mcpath)
            log(f"{_utcnow()} multichip -> {os.path.relpath(mcpath, REPO)}")
        ak = _extract_anakin(bout)
        if ak is not None:
            akpath = anakin_artifact or os.path.join(REPO, "ANAKIN_pr9.json")
            with open(akpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "anakin": ak,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(akpath)
            log(f"{_utcnow()} anakin -> {os.path.relpath(akpath, REPO)}")
        cp = _extract_compile(bout)
        if cp is not None:
            cppath = compile_artifact or os.path.join(REPO, "COMPILE_pr10.json")
            with open(cppath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "compile": cp,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(cppath)
            log(f"{_utcnow()} compile -> {os.path.relpath(cppath, REPO)}")
        px = _extract_prefix(bout)
        if px is not None:
            pxpath = prefix_artifact or os.path.join(REPO, "PREFIX_pr11.json")
            with open(pxpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "prefix": px,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(pxpath)
            log(f"{_utcnow()} prefix -> {os.path.relpath(pxpath, REPO)}")
        sp = _extract_spec(bout)
        if sp is not None:
            sppath = spec_artifact or os.path.join(REPO, "SPEC_pr16.json")
            with open(sppath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "spec": sp,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(sppath)
            log(f"{_utcnow()} spec -> {os.path.relpath(sppath, REPO)}")
        kn = _extract_kernels(bout)
        if kn is not None:
            knpath = kernels_artifact or os.path.join(REPO, "KERNELS_pr17.json")
            with open(knpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "kernels": kn,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(knpath)
            log(f"{_utcnow()} kernels -> {os.path.relpath(knpath, REPO)}")
        ob = _extract_obs(bout)
        if ob is not None:
            obpath = obs_artifact or os.path.join(REPO, "OBS_pr12.json")
            with open(obpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "obs": ob,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(obpath)
            log(f"{_utcnow()} obs -> {os.path.relpath(obpath, REPO)}")
        ia = _extract_ir_audit(bout)
        if ia:
            iapath = audit_artifact or os.path.join(REPO, "AUDIT_pr15.json")
            with open(iapath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "ir_audit": ia,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(iapath)
            log(f"{_utcnow()} ir_audit -> {os.path.relpath(iapath, REPO)}")
        pf = _extract_profiling(bout)
        if pf is not None:
            pfpath = profiling_artifact or os.path.join(REPO, "PROF_pr18.json")
            with open(pfpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "profiling": pf,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(pfpath)
            log(f"{_utcnow()} profiling -> {os.path.relpath(pfpath, REPO)}")
        az = _extract_autoscale(bout)
        if az is not None:
            azpath = autoscale_artifact or os.path.join(
                REPO, "AUTOSCALE_pr19.json"
            )
            with open(azpath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "autoscale": az,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(azpath)
            log(f"{_utcnow()} autoscale -> {os.path.relpath(azpath, REPO)}")
        rp = _extract_replay(bout)
        if rp is not None:
            rppath = replay_artifact or os.path.join(REPO, "REPLAY_pr20.json")
            with open(rppath, "w") as f:
                json.dump(
                    {
                        "artifact": os.path.relpath(path, REPO),
                        "generated": _utcnow(),
                        "replay_shard": rp,
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            paths.append(rppath)
            log(f"{_utcnow()} replay_shard -> {os.path.relpath(rppath, REPO)}")
        if hasattr(runner, "rlint"):
            # PR-8: keep the static-analysis summary current alongside the
            # perf artifacts — the same commit that records a measurement
            # re-records the findings ledger it was measured under
            rlpath = rlint_artifact or os.path.join(REPO, "RLINT_pr15.json")
            rrc, _ = runner.rlint(rlpath)
            if os.path.exists(rlpath):
                paths.append(rlpath)
            log(
                f"{_utcnow()} rlint rc={rrc} -> {os.path.relpath(rlpath, REPO)}"
                + (" (UNSUPPRESSED FINDINGS)" if rrc != 0 else "")
            )
        if hasattr(runner, "sentry"):
            # PR-18: gate the artifact series this commit just (re)wrote —
            # the measurement and the regression verdict it produced land
            # in the same commit, so a perf regression is never silently
            # recorded
            sepath = sentry_artifact or os.path.join(REPO, "PERF_HISTORY.json")
            src, _ = runner.sentry(sepath)
            if os.path.exists(sepath):
                paths.append(sepath)
            log(
                f"{_utcnow()} sentry rc={src} -> {os.path.relpath(sepath, REPO)}"
                + (" (PERF REGRESSION)" if src != 0 else "")
            )
        if commit:
            crc = runner.commit(
                paths,
                f"bench: record BENCH_MODE=all artifact {os.path.basename(path)} "
                "from first healthy relay probe",
            )
            log(f"{_utcnow()} commit rc={crc}")
        return path
    log(f"{_utcnow()} watcher stop (probe budget exhausted)")
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--interval", type=float, default=720.0,
                    help="seconds between probes (round-5 cadence: 12 min)")
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--bench-timeout", type=float, default=900.0)
    ap.add_argument("--max-probes", type=int, default=None)
    ap.add_argument("--artifact", default=None,
                    help="artifact path (default logs/bench_<ts>.jsonl)")
    ap.add_argument("--metrics-artifact", default=None,
                    help="metrics-sections path (default METRICS_pr3.json)")
    ap.add_argument("--multichip-artifact", default=None,
                    help="multichip scaling-sweep path (default MULTICHIP_r06.json)")
    ap.add_argument("--anakin-artifact", default=None,
                    help="anakin fused-fleet sweep path (default ANAKIN_pr9.json)")
    ap.add_argument("--compile-artifact", default=None,
                    help="cold/warm startup split path (default COMPILE_pr10.json)")
    ap.add_argument("--prefix-artifact", default=None,
                    help="prefix-KV reuse result path (default PREFIX_pr11.json)")
    ap.add_argument("--spec-artifact", default=None,
                    help="speculative-decoding A/B path (default SPEC_pr16.json)")
    ap.add_argument("--kernels-artifact", default=None,
                    help="Pallas kernel-tier A/B path (default KERNELS_pr17.json)")
    ap.add_argument("--obs-artifact", default=None,
                    help="fleet trace/SLO/flight-record path (default OBS_pr12.json)")
    ap.add_argument("--audit-artifact", default=None,
                    help="IR-audit predicted-vs-measured MFU path (default AUDIT_pr15.json)")
    ap.add_argument("--profiling-artifact", default=None,
                    help="profiler/drift distillation path (default PROF_pr18.json)")
    ap.add_argument("--autoscale-artifact", default=None,
                    help="elastic-fleet A/B path (default AUTOSCALE_pr19.json)")
    ap.add_argument("--replay-artifact", default=None,
                    help="sharded replay A/B path (default REPLAY_pr20.json)")
    ap.add_argument("--sentry-artifact", default=None,
                    help="perf-sentry gate roll-up path (default PERF_HISTORY.json)")
    ap.add_argument("--rlint-artifact", default=None,
                    help="rlint findings-summary path (default RLINT_pr15.json)")
    ap.add_argument("--no-commit", action="store_true")
    ap.add_argument("--log-file", default=os.path.join(REPO, "logs", "relay_watch.log"))
    args = ap.parse_args(argv)

    os.makedirs(os.path.dirname(args.log_file), exist_ok=True)
    lf = open(args.log_file, "a", buffering=1)

    def log(line: str) -> None:
        print(line, flush=True)
        lf.write(line + "\n")

    path = watch(
        Runner(), log,
        interval=args.interval,
        probe_timeout=args.probe_timeout,
        bench_timeout=args.bench_timeout,
        max_probes=args.max_probes,
        artifact=args.artifact,
        metrics_artifact=args.metrics_artifact,
        multichip_artifact=args.multichip_artifact,
        anakin_artifact=args.anakin_artifact,
        compile_artifact=args.compile_artifact,
        prefix_artifact=args.prefix_artifact,
        spec_artifact=args.spec_artifact,
        kernels_artifact=args.kernels_artifact,
        obs_artifact=args.obs_artifact,
        audit_artifact=args.audit_artifact,
        profiling_artifact=args.profiling_artifact,
        autoscale_artifact=args.autoscale_artifact,
        replay_artifact=args.replay_artifact,
        sentry_artifact=args.sentry_artifact,
        rlint_artifact=args.rlint_artifact,
        commit=not args.no_commit,
    )
    return 0 if path is not None else 1


if __name__ == "__main__":
    raise SystemExit(main())
