#!/usr/bin/env python
"""rlint CLI: JAX/thread-discipline static analysis for rl_tpu.

Usage::

    python tools/rlint.py rl_tpu/                 # gate: exit 1 on unsuppressed
    python tools/rlint.py rl_tpu/ --list          # show suppressed findings too
    python tools/rlint.py rl_tpu/ --no-baseline   # raw findings, no gating
    python tools/rlint.py rl_tpu/ --rule R001     # one rule only
    python tools/rlint.py rl_tpu/ --write-baseline --reason "cold path: ..."
    python tools/rlint.py rl_tpu/ --artifact RLINT_pr8.json

The baseline (``.rlint-baseline.json`` at the repo root) is the triage
ledger: suppressions need a reason, stale entries are warnings. The
``--artifact`` mode writes the bench.py-style committed summary
(findings by rule, fixed vs suppressed) that tools/relay_watch.py keeps
current.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rl_tpu.analysis import (  # noqa: E402
    ALL_RULES,
    Baseline,
    DEFAULT_BASELINE,
    analyze_paths,
)


def build_artifact(findings, unsup, sup, baseline: Baseline, paths) -> dict:
    by_rule = {}
    for rid in ALL_RULES:
        found = [f for f in findings if f.rule == rid]
        by_rule[rid] = {
            "found": len(found),
            "suppressed": sum(1 for f in sup if f.rule == rid),
            "unsuppressed": sum(1 for f in unsup if f.rule == rid),
        }
    fixed_by_rule: dict = {}
    for entry in baseline.fixed:
        fixed_by_rule[entry.get("rule", "?")] = fixed_by_rule.get(entry.get("rule", "?"), 0) + 1
    return {
        "tool": "rlint",
        "paths": list(paths),
        "rules": list(ALL_RULES),
        "by_rule": by_rule,
        "total": {
            "found": len(findings),
            "suppressed": len(sup),
            "unsuppressed": len(unsup),
            "fixed_in_prs": len(baseline.fixed),
        },
        "fixed_by_rule": fixed_by_rule,
        "fixed": baseline.fixed,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    ap.add_argument("paths", nargs="+", help="files or directories to analyze")
    ap.add_argument("--baseline", default=os.path.join(REPO, DEFAULT_BASELINE))
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding; no suppression, no gating exit code")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to a rule id (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="also print suppressed findings (with their reasons)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="add current unsuppressed findings to the baseline")
    ap.add_argument("--reason", default="TODO: triage",
                    help="reason recorded for --write-baseline additions")
    ap.add_argument("--json", default=None, help="dump findings as JSON to a file")
    ap.add_argument("--artifact", default=None,
                    help="write the committed summary artifact (e.g. RLINT_pr8.json)")
    args = ap.parse_args(argv)

    findings = analyze_paths(args.paths, rules=args.rule, root=REPO)

    if args.no_baseline:
        for f in findings:
            print(f.format())
        print(f"rlint: {len(findings)} finding(s), baseline not applied")
        return 0

    baseline = Baseline.load(args.baseline)
    unsup, sup, stale = baseline.split(findings)

    if args.write_baseline:
        for f in unsup:
            baseline.add(f, args.reason)
        baseline.save(args.baseline)
        print(f"rlint: baseline updated with {len(unsup)} suppression(s) -> {args.baseline}")
        unsup, sup, stale = baseline.split(findings)

    if args.list:
        reasons = {s["fingerprint"]: s.get("reason", "") for s in baseline.suppressions}
        for f in sup:
            print(f"SUPPRESSED {f.format()}  reason: {reasons.get(f.fingerprint, '?')}")
    for f in unsup:
        print(f.format())
    for s in stale:
        print(
            f"rlint: warning: stale suppression {s.get('fingerprint')} "
            f"({s.get('rule')} {s.get('file')} [{s.get('qualname')}]) — "
            "the finding no longer fires; consider removing it",
            file=sys.stderr,
        )

    if args.json:
        with open(args.json, "w") as f:
            json.dump([x.to_dict() for x in findings], f, indent=2)
            f.write("\n")
    if args.artifact:
        art = build_artifact(findings, unsup, sup, baseline, args.paths)
        with open(args.artifact, "w") as f:
            json.dump(art, f, indent=2, sort_keys=False)
            f.write("\n")
        print(f"rlint: artifact -> {args.artifact}")

    n_sup = len(sup)
    print(
        f"rlint: {len(findings)} finding(s): {len(unsup)} unsuppressed, "
        f"{n_sup} suppressed, {len(stale)} stale suppression(s)"
    )
    return 1 if unsup else 0


if __name__ == "__main__":
    raise SystemExit(main())
