#!/usr/bin/env python
"""rlint CLI: JAX/thread-discipline static analysis for rl_tpu.

Usage::

    python tools/rlint.py rl_tpu/                 # gate: exit 1 on unsuppressed
    python tools/rlint.py rl_tpu/ --list          # show suppressed findings too
    python tools/rlint.py rl_tpu/ --no-baseline   # raw findings, no gating
    python tools/rlint.py rl_tpu/ --rule R001     # one rule only
    python tools/rlint.py rl_tpu/ --ir            # + compile & audit the IR set
    python tools/rlint.py rl_tpu/ --diff HEAD~1   # only what the revision touched
    python tools/rlint.py rl_tpu/ --strict        # stale suppressions fail too
    python tools/rlint.py rl_tpu/ --write-baseline --reason "cold path: ..."
    python tools/rlint.py rl_tpu/ --artifact RLINT_pr15.json

Two tiers share one baseline and one gate:

- **AST** (R001–R007) lints source files.
- **IR** (R101–R105, ``--ir``) compiles the framework's registered hot
  programs (serving / Anakin / async off-policy — the
  ``rl_tpu.compile.auditset`` set) through a throwaway executable store
  and audits each lowered jaxpr + HLO: host callbacks, unhonored
  donation, shard-local collectives, f64 creep, dead computation.

``--diff <rev>`` scopes both tiers to the change: AST findings are
reported only for the ``.py`` files the revision touched (the index
stays package-wide so call-graph reachability matches a full run), and
the IR set reuses the *persistent* executable store so programs whose
fingerprint/signature did not change reload their serialized
executable and skip re-audit.

The baseline (``.rlint-baseline.json`` at the repo root) is the triage
ledger: suppressions need a reason, stale entries are warnings
(failures under ``--strict``). The ``--artifact`` mode writes the
bench.py-style committed summary (findings by rule, fixed vs
suppressed, IR audit roll-up) that tools/relay_watch.py keeps current.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rl_tpu.analysis import (  # noqa: E402
    ALL_RULES,
    Baseline,
    DEFAULT_BASELINE,
    IR_RULES,
    analyze_paths,
)

# a --diff touching any of these prefixes can change what the registry
# lowers, so the IR set must re-run (store reuse keeps it incremental)
IR_SENSITIVE = (
    "rl_tpu/compile/",
    "rl_tpu/analysis/ir",
    "rl_tpu/models/",
    "rl_tpu/trainers/",
    "rl_tpu/objectives/",
    "rl_tpu/modules/",
    "rl_tpu/collectors/",
    "rl_tpu/data/",
    "rl_tpu/envs/",
    "rl_tpu/parallel/",
)


def changed_files(rev: str) -> list[str]:
    """Repo-relative paths the working tree changed vs ``rev`` (diff +
    untracked, so a not-yet-committed new module is still linted)."""
    diff = subprocess.run(
        ["git", "diff", "--name-only", rev, "--", "."],
        cwd=REPO, capture_output=True, text=True, check=True,
    ).stdout.split()
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=REPO, capture_output=True, text=True, check=True,
    ).stdout.split()
    seen: dict[str, None] = {}
    for p in diff + untracked:
        seen.setdefault(p, None)
    return list(seen)


def run_ir(baseline_path: str, *, fresh_store: bool) -> tuple:
    """Compile the audit set; returns ``(auditor, status)``. The auditor
    carries its own baseline so IR findings merge into the same gate."""
    from rl_tpu.analysis.ir import IRAuditor
    from rl_tpu.compile.auditset import run_ir_audit

    auditor = IRAuditor(baseline_path=baseline_path)
    return run_ir_audit(auditor=auditor, fresh_store=fresh_store)


def build_artifact(findings, unsup, sup, baseline: Baseline, paths,
                   ir_auditor=None, ir_status=None) -> dict:
    rules = list(ALL_RULES) + (list(IR_RULES) if ir_auditor is not None else [])
    by_rule = {}
    for rid in rules:
        found = [f for f in findings if f.rule == rid]
        by_rule[rid] = {
            "found": len(found),
            "suppressed": sum(1 for f in sup if f.rule == rid),
            "unsuppressed": sum(1 for f in unsup if f.rule == rid),
        }
    fixed_by_rule: dict = {}
    for entry in baseline.fixed:
        fixed_by_rule[entry.get("rule", "?")] = fixed_by_rule.get(entry.get("rule", "?"), 0) + 1
    art = {
        "tool": "rlint",
        "paths": list(paths),
        "rules": rules,
        "by_rule": by_rule,
        "total": {
            "found": len(findings),
            "suppressed": len(sup),
            "unsuppressed": len(unsup),
            "fixed_in_prs": len(baseline.fixed),
        },
        "fixed_by_rule": fixed_by_rule,
        "fixed": baseline.fixed,
    }
    if ir_auditor is not None:
        by_program = {}
        for rep in sorted(ir_auditor._snapshot(), key=lambda r: r.name):
            d = {
                "findings": len(rep.findings),
                "donated_declared": rep.donated_declared,
                "donated_honored": rep.donated_honored,
            }
            if rep.cost is not None:
                d["flops"] = rep.cost.flops
                d["bytes"] = rep.cost.bytes
            by_program[rep.name] = d
        art["ir"] = {
            "status": dict(ir_status or {}),
            "programs_audited": ir_auditor.programs_audited(),
            "by_program": by_program,
        }
    return art


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1].strip())
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to analyze (default: rl_tpu/)")
    ap.add_argument("--baseline", default=os.path.join(REPO, DEFAULT_BASELINE))
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding; no suppression, no gating exit code")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to a rule id (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="also print suppressed findings (with their reasons)")
    ap.add_argument("--ir", action="store_true",
                    help="compile the rl_tpu.compile.auditset programs through a "
                         "fresh executable store and gate the R101-R105 IR rules")
    ap.add_argument("--diff", metavar="REV", default=None,
                    help="lint only files changed vs REV; the IR set runs (with "
                         "the persistent store, so unchanged programs skip) only "
                         "when IR-sensitive modules changed")
    ap.add_argument("--strict", action="store_true",
                    help="stale baseline suppressions fail the gate (exit 1)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="add current unsuppressed findings to the baseline")
    ap.add_argument("--reason", default="TODO: triage",
                    help="reason recorded for --write-baseline additions")
    ap.add_argument("--json", default=None, help="dump findings as JSON to a file")
    ap.add_argument("--artifact", default=None,
                    help="write the committed summary artifact (e.g. RLINT_pr15.json)")
    args = ap.parse_args(argv)
    paths = args.paths or [os.path.join(REPO, "rl_tpu")]

    run_ast = True
    run_the_ir = args.ir
    fresh_store = True
    diff_scope: set | None = None
    if args.diff is not None:
        changed = changed_files(args.diff)
        py = [
            p for p in changed
            if p.endswith(".py") and p.startswith("rl_tpu/") and
            os.path.exists(os.path.join(REPO, p))
        ]
        if py:
            # the call-graph index must stay PACKAGE-wide even for a scoped
            # run: analyzing one file alone changes unique-method-name call
            # resolution (a method unique within the file but ambiguous in
            # the package would grow a hot edge a full run never has), so
            # only the *reporting* is scoped to the changed files
            diff_scope = set(py)
        else:
            run_ast = False
        ir_hit = sorted(
            p for p in changed
            if p.endswith(".py") and p.startswith(IR_SENSITIVE)
        )
        if ir_hit:
            run_the_ir = True
            fresh_store = False  # unchanged fingerprints reload + skip audit
            print(f"rlint: --diff {args.diff}: {len(py)} changed file(s), "
                  f"IR set re-runs ({ir_hit[0]}{' …' if len(ir_hit) > 1 else ''})")
        else:
            print(f"rlint: --diff {args.diff}: {len(py)} changed file(s), "
                  "no IR-sensitive modules touched")

    findings = analyze_paths(paths, rules=args.rule, root=REPO) if run_ast else []
    if diff_scope is not None:
        findings = [f for f in findings if f.file in diff_scope]

    ir_auditor = None
    ir_status: dict = {}
    if run_the_ir and (args.rule is None or any(r in IR_RULES for r in args.rule)):
        ir_auditor, ir_status = run_ir(
            "" if args.no_baseline else args.baseline, fresh_store=fresh_store
        )
        ir_findings = ir_auditor.findings()
        if args.rule is not None:
            ir_findings = [f for f in ir_findings if f.rule in args.rule]
        findings = findings + sorted(
            ir_findings, key=lambda f: (f.file, f.line, f.rule)
        )
        failures = {k: v for k, v in ir_status.items() if v != "ok"}
        for name, why in failures.items():
            print(f"rlint: error: IR audit target {name!r}: {why}", file=sys.stderr)
        print(f"rlint: IR set: {ir_auditor.programs_audited()} program(s) audited, "
              f"{len(ir_findings)} finding(s)")

    if args.no_baseline:
        for f in findings:
            print(f.format())
        print(f"rlint: {len(findings)} finding(s), baseline not applied")
        return 0

    baseline = Baseline.load(args.baseline)
    unsup, sup, stale = baseline.split(findings)
    # staleness is only meaningful for files/programs this run actually
    # analyzed: a --diff scoped to three files must not damn every other
    # suppression, and IR-program entries are only live when --ir ran
    if args.diff is not None:
        scope = diff_scope or set()
        stale = [
            s for s in stale
            if s.get("file") in scope
            or (ir_auditor is not None
                and str(s.get("file", "")).startswith("program:"))
        ]
    elif ir_auditor is None:
        stale = [s for s in stale if not str(s.get("file", "")).startswith("program:")]
    # an IR-set builder crash means programs went unaudited — that must
    # not read as "clean"
    ir_broken = any(v != "ok" for v in ir_status.values())

    if args.write_baseline:
        for f in unsup:
            baseline.add(f, args.reason)
        baseline.save(args.baseline)
        print(f"rlint: baseline updated with {len(unsup)} suppression(s) -> {args.baseline}")
        unsup, sup, stale = baseline.split(findings)

    if args.list:
        reasons = {s["fingerprint"]: s.get("reason", "") for s in baseline.suppressions}
        for f in sup:
            print(f"SUPPRESSED {f.format()}  reason: {reasons.get(f.fingerprint, '?')}")
    for f in unsup:
        print(f.format())
    for s in stale:
        sev = "error" if args.strict else "warning"
        print(
            f"rlint: {sev}: stale suppression {s.get('fingerprint')} "
            f"({s.get('rule')} {s.get('file')} [{s.get('qualname')}]) — "
            "the finding no longer fires; remove it from the baseline",
            file=sys.stderr,
        )

    if args.json:
        with open(args.json, "w") as f:
            json.dump([x.to_dict() for x in findings], f, indent=2)
            f.write("\n")
    if args.artifact:
        art = build_artifact(findings, unsup, sup, baseline, paths,
                             ir_auditor=ir_auditor, ir_status=ir_status)
        with open(args.artifact, "w") as f:
            json.dump(art, f, indent=2, sort_keys=False)
            f.write("\n")
        print(f"rlint: artifact -> {args.artifact}")

    n_sup = len(sup)
    print(
        f"rlint: {len(findings)} finding(s): {len(unsup)} unsuppressed, "
        f"{n_sup} suppressed, {len(stale)} stale suppression(s)"
    )
    if unsup or ir_broken:
        return 1
    if args.strict and stale:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
